"""End-to-end integration: real worker subprocesses on the CPU backend.

This is the test tier the reference only declared in packaging but never
shipped (SURVEY §4): spawn N actual worker processes, form a real
``jax.distributed`` world with cross-process gloo collectives (the
CUDA→Gloo fallback analog, reference: worker.py:146-149), and drive the
full control plane: execute, streaming, variables, sync, status, death.
"""

import time

import numpy as np
import pytest

from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
from nbdistributed_tpu.messaging import CommunicationManager, WorkerDied

pytestmark = [pytest.mark.integration]

WORLD = 2
ATTACH_TIMEOUT = 120  # worker startup imports jax (~5s) + rendezvous


@pytest.fixture(scope="module")
def cluster():
    comm = CommunicationManager(num_workers=WORLD, timeout=60)
    pm = ProcessManager()
    pm.add_death_callback(lambda rank, rc: comm.mark_worker_dead(rank))
    try:
        pm.start_workers(WORLD, comm.port, backend="cpu")
        wait_until_ready(comm, pm, ATTACH_TIMEOUT)
    except Exception:
        pm.shutdown()
        comm.shutdown()
        raise
    yield comm, pm
    comm.post(list(range(WORLD)), "shutdown")
    time.sleep(0.5)
    pm.shutdown()
    comm.shutdown()


def outputs(responses):
    return {r: m.data.get("output") for r, m in responses.items()}


def test_execute_on_all_ranks(cluster):
    comm, _ = cluster
    out = outputs(comm.send_to_all("execute", "rank * 10 + 1"))
    assert out == {0: "1", 1: "11"}


def test_namespace_persists(cluster):
    comm, _ = cluster
    comm.send_to_all("execute", "stash = rank + 100")
    out = outputs(comm.send_to_all("execute", "stash"))
    assert out == {0: "100", 1: "101"}


def test_world_formed(cluster):
    comm, _ = cluster
    out = outputs(comm.send_to_all("execute", "jax.device_count()"))
    assert out == {0: str(WORLD), 1: str(WORLD)}


def test_cross_process_all_reduce(cluster):
    comm, _ = cluster
    out = outputs(comm.send_to_all(
        "execute",
        "r = all_reduce(jnp.ones(4) * (rank + 1))\nfloat(r[0])",
        timeout=180))
    # ranks contribute 1s and 2s -> everyone sees 3.0
    assert out == {0: "3.0", 1: "3.0"}


def test_cross_process_all_gather(cluster):
    comm, _ = cluster
    out = outputs(comm.send_to_all(
        "execute", "g = all_gather(jnp.float32(rank))\ng.shape[0]",
        timeout=180))
    assert out == {0: str(WORLD), 1: str(WORLD)}


def test_broadcast_from_root(cluster):
    comm, _ = cluster
    comm.send_to_ranks([0], "execute", "payload = jnp.arange(3.0) + 7")
    comm.send_to_ranks([1], "execute", "payload = jnp.zeros(3)")
    out = outputs(comm.send_to_all(
        "execute", "payload = broadcast(payload, root=0)\nfloat(payload[0])",
        timeout=180))
    assert out == {0: "7.0", 1: "7.0"}


def test_streaming_output_arrives_during_execution(cluster):
    comm, _ = cluster
    got = []
    comm.set_output_callback(lambda rank, d: got.append((rank, d)))
    comm.send_to_all("execute",
                     "import time\nfor i in range(3):\n"
                     "    print('tick', i)\n    time.sleep(0.05)")
    texts = [d["text"].strip() for _, d in got if d["stream"] == "stdout"]
    assert texts.count("tick 0") == WORLD
    assert texts.count("tick 2") == WORLD
    comm.set_output_callback(lambda rank, d: None)


def test_get_var_array_roundtrip(cluster):
    comm, _ = cluster
    comm.send_to_all("execute", "w = jnp.arange(6.0).reshape(2, 3) * (rank+1)")
    resp = comm.send_to_rank(1, "get_var", "w")
    assert resp.data["array"] and resp.data["shape"] == [2, 3]
    np.testing.assert_allclose(
        resp.bufs["value"], np.arange(6.0).reshape(2, 3) * 2)


def test_set_var_pushes_array(cluster):
    comm, _ = cluster
    comm.send_to_all("set_var", {"name": "injected"},
                     bufs={"value": np.full((2, 2), 5.0, np.float32)})
    out = outputs(comm.send_to_all("execute", "float(injected.sum())"))
    assert out == {0: "20.0", 1: "20.0"}


def test_get_var_missing_name(cluster):
    comm, _ = cluster
    resp = comm.send_to_rank(0, "get_var", "no_such_name")
    assert "error" in resp.data


def test_sync_barrier(cluster):
    comm, _ = cluster
    resp = comm.send_to_all("sync", timeout=120)
    assert all(m.data["status"] == "synced" for m in resp.values())


def test_status_probe(cluster):
    comm, _ = cluster
    resp = comm.send_to_rank(0, "get_status")
    st = resp.data
    assert st["rank"] == 0
    assert st["world_size"] == WORLD
    assert st["backend"] == "cpu"
    assert st["global_device_count"] == WORLD


def test_namespace_info(cluster):
    comm, _ = cluster
    comm.send_to_all("execute", "probe_arr = jnp.zeros((3, 4))")
    resp = comm.send_to_rank(0, "get_namespace_info")
    info = resp.data["namespace_info"]
    assert info["probe_arr"]["kind"] == "array"
    assert info["probe_arr"]["shape"] == [3, 4]
    assert info["rank"]["kind"] == "scalar"
    assert info["all_reduce"]["kind"] == "callable"


def test_error_cell_reports_per_rank(cluster):
    comm, _ = cluster
    resp = comm.send_to_all("execute", "1 / 0")
    for m in resp.values():
        assert "ZeroDivisionError" in m.data["traceback"]
    # workers stay healthy afterwards
    out = outputs(comm.send_to_all("execute", "'alive'"))
    assert out == {0: "'alive'", 1: "'alive'"}


def test_checkpoint_save_restore_roundtrip(cluster, tmp_path):
    comm, _ = cluster
    path = str(tmp_path / "ck")
    comm.send_to_all("execute",
                     "ck_w = jnp.ones((2, 3)) * (rank + 1)\n"
                     "ck_step = 40 + rank")
    resp = comm.send_to_all("checkpoint", {"action": "save", "path": path,
                                           "names": ["ck_w", "ck_step"]})
    for m in resp.values():
        assert m.data["status"] == "save", m.data
        assert m.data["summary"]["ck_w"]["bytes"] == 24
    # clobber, then restore and verify per-rank values came back
    comm.send_to_all("execute", "ck_w = None; ck_step = None")
    resp = comm.send_to_all("checkpoint",
                            {"action": "restore", "path": path,
                             "names": None})
    for m in resp.values():
        assert m.data["status"] == "restore", m.data
    out = outputs(comm.send_to_all(
        "execute", "(float(ck_w[0, 0]), ck_step)"))
    assert out == {0: "(1.0, 40)", 1: "(2.0, 41)"}


def test_checkpoint_missing_name_errors_cleanly(cluster, tmp_path):
    comm, _ = cluster
    resp = comm.send_to_all(
        "checkpoint", {"action": "save", "path": str(tmp_path / "ck2"),
                       "names": ["no_such_var"]})
    for m in resp.values():
        assert "no_such_var" in m.data["error"]


def test_multihost_local_plan_runs_real_workers():
    """Drive the multi-host code path end-to-end with 'local' hosts:
    the plan's argv/env must bring up a real 2-process world."""
    comm = CommunicationManager(num_workers=2, timeout=60)
    pm = ProcessManager()
    pm.add_death_callback(lambda rank, rc: comm.mark_worker_dead(rank))
    try:
        world = pm.start_workers_multihost(
            "local:2", comm.port, coordinator_host="127.0.0.1",
            backend="cpu")
        assert world == 2
        wait_until_ready(comm, pm, ATTACH_TIMEOUT)
        out = outputs(comm.send_to_all("execute", "rank + 40"))
        assert out == {0: "40", 1: "41"}
        out = outputs(comm.send_to_all(
            "execute", "float(all_reduce(jnp.ones(2))[0])", timeout=180))
        assert out == {0: "2.0", 1: "2.0"}
    finally:
        comm.post([0, 1], "shutdown")
        time.sleep(0.5)
        pm.shutdown()
        comm.shutdown()


def test_reduce_scatter_psum_scatter_path(cluster):
    """One device per process -> the true psum_scatter collective."""
    comm, _ = cluster
    out = outputs(comm.send_to_all(
        "execute",
        "rs = reduce_scatter(jnp.arange(4.0) + rank)\n"
        "[float(v) for v in rs]", timeout=180))
    # sum over ranks: [0+1, 1+2, 2+3, 3+4] = [1,3,5,7]; rank r gets
    # chunk r of the leading axis (2 elements each).
    assert out == {0: "[1.0, 3.0]", 1: "[5.0, 7.0]"}


def test_all_reduce_quantized_cross_process(cluster):
    comm, _ = cluster
    out = outputs(comm.send_to_all(
        "execute",
        "q = all_reduce_quantized(jnp.ones(300) * (rank + 1))\n"
        "round(float(q.mean()), 2)", timeout=180))
    # exact sum = 3.0 everywhere; int8 blockwise keeps it within 1%
    assert all(2.9 < float(v) < 3.1 for v in out.values()), out


def test_reduce_scatter_fallback_op_max(cluster):
    """Non-sum ops use the all_reduce+slice fallback path."""
    comm, _ = cluster
    out = outputs(comm.send_to_all(
        "execute",
        "rm = reduce_scatter(jnp.arange(4.0) * (rank + 1), op='max')\n"
        "[float(v) for v in rm]", timeout=180))
    # elementwise max over ranks = [0,2,4,6]; rank r gets chunk r
    assert out == {0: "[0.0, 2.0]", 1: "[4.0, 6.0]"}


def test_heartbeat_carries_busy_state(cluster):
    """The serial worker loop cannot answer probes mid-cell, so the
    heartbeat thread reports busy state out-of-band: during a long
    execute, pings carry {busy_type, busy_s} with busy_s growing;
    after completion they go back to idle (no payload)."""
    import threading

    comm, _ = cluster
    done = threading.Event()

    def _send():
        comm.send_to_all("execute",
                         "import time\ntime.sleep(7)\n'long done'",
                         timeout=120)
        done.set()

    t = threading.Thread(target=_send, daemon=True)
    t.start()
    try:
        # Wait for a ping that reports the execute in progress.
        deadline = time.time() + 30
        seen = None
        while time.time() < deadline:
            ping = comm.last_ping(0)
            if ping and ping[1].get("busy_type") == "execute":
                seen = ping[1]
                break
            time.sleep(0.2)
        assert seen is not None, "no busy ping within 30s"
        assert seen["busy_s"] >= 0
        # A later ping must show the busy time growing.
        first = seen["busy_s"]
        deadline = time.time() + 20
        while time.time() < deadline:
            ping = comm.last_ping(0)
            if ping[1].get("busy_s", -1) > first + 1.0:
                break
            time.sleep(0.2)
        else:
            raise AssertionError("busy_s did not grow across pings")
    finally:
        assert done.wait(60), "long cell never completed"
        t.join(timeout=10)
    # Idle again: the next ping drops the busy payload.  (The
    # collective-position piggyback — "col", the hang watchdog's
    # skew signal — legitimately persists while idle; only the busy
    # fields must clear.)
    deadline = time.time() + 15
    while time.time() < deadline:
        ping = comm.last_ping(0)
        if ping and ping[1].get("busy_s") is None:
            break
        time.sleep(0.2)
    else:
        raise AssertionError(f"ping still busy after completion: "
                             f"{comm.last_ping(0)}")


def test_interrupt_aborts_cell_workers_survive(cluster):
    """%dist_interrupt semantics: SIGINT aborts the running cell with a
    KeyboardInterrupt error response; the workers keep serving."""
    import threading

    comm, pm = cluster
    result = {}

    def _send():
        result.update(comm.send_to_all(
            "execute", "import time\nfor _ in range(600):\n"
                       "    time.sleep(0.1)", timeout=120))

    t = threading.Thread(target=_send, daemon=True)
    t.start()
    time.sleep(1.0)  # let the cell start running
    signaled = pm.interrupt()
    assert signaled == [0, 1]
    t.join(timeout=30)
    assert not t.is_alive(), "interrupt did not abort the cell"
    for m in result.values():
        assert "KeyboardInterrupt" in m.data["error"]
    out = outputs(comm.send_to_all("execute", "'still here'"))
    assert out == {0: "'still here'", 1: "'still here'"}


def test_interrupt_while_idle_is_harmless(cluster):
    comm, pm = cluster
    pm.interrupt()
    time.sleep(0.5)
    out = outputs(comm.send_to_all("execute", "1 + 1"))
    assert out == {0: "2", 1: "2"}


def test_interrupt_storm_no_deaths_no_lost_replies(cluster):
    """Regression for the three interrupt races fixed in rounds 2-3:
    (a) a deferred KeyboardInterrupt surfacing outside the designed
    windows killed the worker or dropped a reply; (b) a KI between
    sock.recv and the buffer append lost bytes, desynced the stream,
    and made the coordinator declare a live worker dead; (c) the
    round-2 tail race — a SIGINT delivered to a lazily-spawned,
    mask-unblocked XLA/gloo thread defeated the main thread's pthread
    mask and escaped the run loop as a BaseException mid-dispatch
    (root-caused and closed in round 3 by the Python-level gated
    handler, runtime/interrupt.py; the module context mattered because
    earlier tests' cells had compiled JAX programs, spawning exactly
    those threads).  Rapid idle interrupts interleaved with cells
    hammer all three windows; any TransportError/WorkerDied here is a
    real regression — there is no xfail."""
    comm, pm = cluster
    # The tail race needed SIGINT-unblocked native threads in the
    # worker: force their existence even standalone (a jit compile
    # spawns XLA pool threads during the user-code window).
    warm = comm.send_to_all(
        "execute",
        "_storm_warm = jax.jit(lambda x: (x @ x).sum())"
        "(jnp.ones((64, 64))).block_until_ready()", timeout=120)
    # A silently-failed warm-up would leave no XLA pool threads and
    # reduce this regression test to the already-fixed common paths.
    assert all("error" not in m.data for m in warm.values()), \
        {r: m.data for r, m in warm.items()}
    for i in range(25):
        pm.interrupt(None)
        # The probe must always get a reply per rank: either it ran
        # normally or the late signal aborted it as a clean
        # KeyboardInterrupt error.  A timeout here IS the dropped-
        # reply bug this test exists to catch — never swallow it.
        # Generous deadline: under full-suite CPU contention a slow
        # reply is not the bug class this guards.
        probe = comm.send_to_all("execute", "'probe'", timeout=60)
        for r, m in probe.items():
            ok = (m.data.get("output") == "'probe'"
                  or "KeyboardInterrupt" in (m.data.get("error")
                                             or ""))
            assert ok, (i, r, m.data)
        out = outputs(comm.send_to_all("execute", f"{i} * 2",
                                       timeout=60))
        assert out == {r: str(i * 2) for r in range(WORLD)}, (i, out)
    assert pm.alive_ranks() == list(range(WORLD))


def test_params_pytree_pull_push_without_pickle():
    """VERDICT r4 #6 done-bar: a model-params pytree crosses an
    allow_pickle=False control plane — treedef as JSON, leaves as raw
    buffers — and round-trips arrays + structure exactly.  A 1-worker
    world with pickle DISABLED on the coordinator channel: any pickle
    fallback would raise CodecError at decode."""
    import jax

    from nbdistributed_tpu.messaging.codec import unflatten_pytree_wire

    comm = CommunicationManager(num_workers=1, timeout=60,
                                allow_pickle=False)
    pm = ProcessManager()
    pm.add_death_callback(lambda rank, rc: comm.mark_worker_dead(rank))
    try:
        pm.start_workers(1, comm.port, backend="cpu")
        wait_until_ready(comm, pm, ATTACH_TIMEOUT)
        comm.send_to_all(
            "execute",
            "from nbdistributed_tpu.models import init_params, "
            "tiny_config\n"
            "_cfg = tiny_config()\n"
            "params = init_params(jax.random.PRNGKey(0), _cfg)")
        resp = comm.send_to_rank(0, "get_var", "params", timeout=60)
        assert resp.data.get("pytree") is not None, resp.data
        pulled = unflatten_pytree_wire(resp.data["pytree"], resp.bufs)

        # Structure + every leaf must match the same init done here.
        from nbdistributed_tpu.models import init_params, tiny_config
        want = init_params(jax.random.PRNGKey(0), tiny_config())
        assert (jax.tree_util.tree_structure(pulled)
                == jax.tree_util.tree_structure(
                    jax.tree_util.tree_map(np.asarray, want)))
        for got, exp in zip(jax.tree_util.tree_leaves(pulled),
                            jax.tree_util.tree_leaves(want)):
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(exp))

        # Push the pytree back under a new name (same pickle-free
        # path in the other direction) and check a leaf on the worker.
        from nbdistributed_tpu.messaging.codec import flatten_pytree_wire
        meta, bufs = flatten_pytree_wire(pulled)
        comm.send_to_rank(0, "set_var",
                          {"name": "params2", "pytree": meta},
                          bufs=bufs, timeout=60)
        out = comm.send_to_rank(0, "execute",
                                "bool(jnp.array_equal(params2['embed'],"
                                " params['embed']))", timeout=60)
        assert out.data["output"] == "True"
    finally:
        comm.post([0], "shutdown")
        time.sleep(0.3)
        pm.shutdown()
        comm.shutdown()


def test_scatter_gather_reduce_cross_process(cluster):
    """dist.scatter/gather/reduce across a real 2-process gloo world:
    scatter hands each rank the ROOT's row (non-root feeds garbage and
    root=1, so a no-communication or root-ignoring implementation
    fails), gather stacks on root only, reduce lands on root only."""
    comm, _ = cluster
    out = outputs(comm.send_to_all(
        "execute",
        "stk = (jnp.stack([jnp.full(2, 10.0), jnp.full(2, 20.0)])\n"
        "       if rank == 1 else jnp.full((2, 2), -99.0))\n"
        "s = dist.scatter(stk, root=1)\n"
        "float(s[0])", timeout=120))
    assert out == {0: "10.0", 1: "20.0"}
    out = outputs(comm.send_to_all(
        "execute",
        "try:\n"
        "    dist.scatter(jnp.zeros((2, 2)), root=5)\n"
        "    bad = 'no raise'\n"
        "except ValueError as e:\n"
        "    bad = 'out of range' in str(e)\n"
        "bad", timeout=120))
    assert out == {0: "True", 1: "True"}
    out = outputs(comm.send_to_all(
        "execute",
        "g = dist.gather(jnp.full(2, rank + 1.0), root=1)\n"
        "'none' if g is None else str(g.shape)", timeout=120))
    assert out == {0: "'none'", 1: "'(2, 2)'"}
    out = outputs(comm.send_to_all(
        "execute",
        "r = dist.reduce(jnp.ones(3) * (rank + 1), root=0)\n"
        "'none' if r is None else str(float(r[0]))", timeout=120))
    assert out == {0: "'3.0'", 1: "'none'"}
