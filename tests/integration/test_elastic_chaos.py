"""Elastic pools under chaos (ISSUE 16), end to end on the CPU
backend.

The headline scenario the tentpole exists for:

1. **2 -> 4 -> 2 resize under 8% frame drops, with a resized-in rank
   SIGKILLed mid-drain.**  A tenant runs counter cells and serves
   generation requests across both resizes while the control plane
   drops 8% of frames in both directions; during the shrink's drain
   barrier, a rank that only joined at epoch 2 is SIGKILLed.  Every
   cell completes exactly once (the worker replay cache dedupes
   same-msg-id redelivery; a per-epoch namespace counter is the
   tripwire), every accepted serving request finishes with its EXACT
   solo-``generate`` greedy tokens (replay across the flip is
   bit-identical), membership advances epoch/generation per resize,
   and the watchdog never blames a draining rank — zero hang
   verdicts.
2. **Chaos-safe tenant migration** between two pools sharing a runs
   root: the live path (export -> import -> release) moves token,
   epoch, and serve journal, and the tenant reattaches at the
   destination with its ORIGINAL token; the dead-source path (the
   manifest's pid fenced to a corpse, as after a SIGKILL) recovers
   the same from what the source durably published, with the release
   step correctly reported as impossible.

Marked ``slow`` on purpose (three fleet spawns); the CI resilience
job owns these (marker ``elastic``).
"""

import ast
import json
import os
import signal
import threading
import time

import pytest

from nbdistributed_tpu.gateway import router as router_mod
from nbdistributed_tpu.gateway.client import TenantClient
from nbdistributed_tpu.gateway.daemon import (GatewayDaemon,
                                              gateway_manifest_path)
from nbdistributed_tpu.gateway.serving import migrated_journal_path
from nbdistributed_tpu.gateway.scheduler import SchedPolicy
from nbdistributed_tpu.observability import flightrec
from nbdistributed_tpu.resilience.faults import FaultPlan

pytestmark = [pytest.mark.integration, pytest.mark.elastic,
              pytest.mark.gateway, pytest.mark.faults,
              pytest.mark.slow]

WORLD = 2          # starting size; the test grows to 4 and back

SPEC = (
    "import jax as _j, jax.numpy as _jn\n"
    "from nbdistributed_tpu.models import tiny_config, init_params\n"
    "cfg = tiny_config(dtype=_jn.float32, use_flash=False)\n"
    "params = init_params(_j.random.PRNGKey(0), cfg)\n")

PROMPTS = [[5, 9, 2], [7, 1], [3, 4, 8, 1], [11, 3],
           [2, 2, 2, 2], [6, 13], [1, 2, 3], [9, 9]]
MAX_NEW = 5

REF_CELL = (
    "import jax as _j, jax.numpy as _jn, numpy as _np\n"
    "from nbdistributed_tpu.models import (tiny_config, init_params, "
    "generate)\n"
    "_cfg = tiny_config(dtype=_jn.float32, use_flash=False)\n"
    "_p = init_params(_j.random.PRNGKey(0), _cfg)\n"
    f"_prompts = {PROMPTS!r}\n"
    f"[[int(t) for t in _np.asarray(generate(_p, _jn.asarray(pr, "
    f"_jn.int32)[None], _cfg, {MAX_NEW}))[0][len(pr):]] "
    "for pr in _prompts]")

# Exactly-once tripwire: each run bumps a namespace counter.  Under
# 8% drops the retry layer redelivers same-msg-id frames; a double
# EXECUTION (not just double delivery) would overshoot the counter.
INC_CELL = "_c = globals().get('_c', 0) + 1\n_c"


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    run_dir = str(tmp_path_factory.mktemp("elasticpool"))
    old = {k: os.environ.get(k)
           for k in ("NBD_RUN_DIR", "NBD_RETRY_TIMEOUT_S",
                     "NBD_RETRY_ATTEMPTS")}
    os.environ["NBD_RUN_DIR"] = run_dir
    # Retry layer ON: the drop phases lean on same-msg-id redelivery
    # + the worker replay cache.
    os.environ["NBD_RETRY_TIMEOUT_S"] = "5"
    os.environ["NBD_RETRY_ATTEMPTS"] = "6"
    flightrec.reset_for_tests()
    gw = GatewayDaemon(
        WORLD, backend="cpu",
        policy=SchedPolicy("fair", mesh_slots=1, tenant_inflight=16,
                           queue_depth=32),
        request_timeout=None, attach_timeout=240.0)
    try:
        yield gw
    finally:
        gw.close()
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def attach(pool, name, **kw):
    return TenantClient(pool.tenant_host, pool.tenant_port, name,
                        pool_token=pool.pool_token, **kw)


def arm_drops(pool) -> None:
    """8% frame drops in both directions: worker plans shape
    worker->gateway, the coordinator plan shapes gateway->worker."""
    live = sorted(set(range(pool.world_size))
                  - pool.comm.dead_ranks())
    pool.comm.send_to_ranks(live, "chaos", {
        "action": "set", "spec": {"seed": 9, "drop": 0.08}},
        timeout=60)
    pool.comm.set_fault_plan(FaultPlan(seed=11, drop=0.08))


def clear_drops(pool) -> None:
    pool.comm.set_fault_plan(None)
    try:
        live = sorted(set(range(pool.world_size))
                      - pool.comm.dead_ranks())
        pool.comm.send_to_ranks(live, "chaos", {"action": "clear"},
                                timeout=60)
    except Exception:
        pass


def counter_values(client, world: int, runs: int) -> list[int]:
    """Run INC_CELL ``runs`` times on all ranks, return the final
    counter read from every rank."""
    ranks = list(range(world))
    for _ in range(runs):
        out = client.execute(INC_CELL, target_ranks=ranks,
                             timeout=180)
        assert not out.get("error"), out
    out = client.execute("_c", target_ranks=ranks, timeout=180)
    results = out.get("results") or {}
    assert len(results) == world, out
    return [ast.literal_eval(results[str(r)]["output"])
            for r in ranks]


def wait_results(client, rids, timeout=300.0) -> dict:
    got: dict = {}
    deadline = time.time() + timeout
    while len(got) < len(rids) and time.time() < deadline:
        for rid in rids:
            if rid in got:
                continue
            r = client.serve_result(rid)
            if r.get("done"):
                got[rid] = r
        time.sleep(0.25)
    return got


# ----------------------------------------------------------------------


def test_resize_2_4_2_chaos_exactly_once(pool):
    t1 = attach(pool, "el1")
    try:
        out = t1.execute(REF_CELL, target_ranks=[0], timeout=300)
        solo = ast.literal_eval(
            (out.get("results") or {})["0"]["output"])

        arm_drops(pool)
        try:
            # Epoch 1, world 2: cells run exactly once under drops.
            assert counter_values(t1, 2, 3) == [3, 3]

            t1.serve_start(SPEC, max_batch=4, max_len=48, pad_to=4,
                           steps=2, queue_depth=32, inflight=32,
                           timeout=600)
            rids = [t1.serve_submit(pr, MAX_NEW)["rid"]
                    for pr in PROMPTS[:4]]

            # Grow 2 -> 4 with serving traffic in flight.  The drain
            # barrier parks the decode loop; the flip re-seeds the
            # spec on the new fleet and replays in-flight requests.
            res = pool.resize(4, reason="chaos-grow")
            assert res["status"] == "resized", res
            assert res == {**res, "world_size": 4, "epoch": 2,
                           "generation": 2}
            mem = pool.status()["membership"]
            assert mem["generation"] == 2 and mem["epoch"] == 2
            assert sorted(mem["ranks"]) == ["0", "1", "2", "3"]
            assert all(v["join_epoch"] == 2 and v["state"] == "active"
                       for v in mem["ranks"].values())
            assert mem["retired_epochs"] == [1]

            # Re-arm worker-side drops on the fresh fleet (the
            # coordinator-side plan survived the flip) and prove
            # exactly-once again on the resized world: namespaces
            # were re-seeded, so the counter restarts from 0.
            arm_drops(pool)
            assert counter_values(t1, 4, 3) == [3, 3, 3, 3]

            rids += [t1.serve_submit(pr, MAX_NEW)["rid"]
                     for pr in PROMPTS[4:]]

            # Shrink 4 -> 2; SIGKILL a resized-in rank (join_epoch 2)
            # the moment the drain barrier opens.  The watchdog must
            # not blame it, the drain must still converge, and no
            # accepted request may be lost or doubled.
            victim_pid = pool.pm.processes[3].pid
            killed = threading.Event()

            def _kill_mid_drain():
                deadline = time.time() + 60
                while time.time() < deadline:
                    if pool.membership.draining:
                        try:
                            os.kill(victim_pid, signal.SIGKILL)
                        except ProcessLookupError:
                            pass
                        killed.set()
                        return
                    time.sleep(0.005)

            killer = threading.Thread(target=_kill_mid_drain,
                                      daemon=True)
            killer.start()
            res = pool.resize(2, reason="chaos-shrink")
            killer.join(timeout=60)
            assert killed.is_set(), \
                "the SIGKILL thread never saw the drain open"
            assert res["status"] == "resized", res
            assert res == {**res, "world_size": 2, "epoch": 3,
                           "generation": 3}

            arm_drops(pool)
            got = wait_results(t1, rids, timeout=300)
        finally:
            clear_drops(pool)

        assert len(got) == len(rids), \
            (f"unfinished requests: {sorted(set(rids) - set(got))}; "
             f"status={t1.serve_status()}")
        # Bit-identical streams: every accepted request completed
        # exactly once with the solo-generate greedy tokens, across
        # two fleet flips and a mid-drain SIGKILL.
        for i, rid in enumerate(rids):
            assert got[rid]["status"] == "completed", got[rid]
            assert got[rid]["tokens"] == solo[i], \
                (f"request {rid} (prompt {PROMPTS[i]}): "
                 f"{got[rid]['tokens']} != solo {solo[i]}")
        st = t1.serve_status()
        assert st["accepted"] == len(rids), st
        assert st["completed"] == len(rids), st

        status = pool.status()
        assert status["world_size"] == 2
        assert status["epoch"] == 3
        mem = status["membership"]
        assert mem["generation"] == 3
        assert sorted(mem["ranks"]) == ["0", "1"]
        assert mem["transition"] is None
        assert mem["retired_epochs"] == [1, 2]
        # The robustness bar: a draining (or SIGKILLed-while-
        # draining) rank is never a hang verdict.
        assert not (status["hang_verdicts"] or []), status
        assert not status["scheduler"].get("paused"), status
    finally:
        try:
            t1.close()
        except Exception:
            pass


# ----------------------------------------------------------------------


def _mini_pool(run_dir: str) -> GatewayDaemon:
    os.environ["NBD_RUN_DIR"] = run_dir
    return GatewayDaemon(
        1, backend="cpu",
        policy=SchedPolicy("fair", mesh_slots=1, tenant_inflight=8,
                           queue_depth=16),
        request_timeout=None, attach_timeout=240.0)


def test_tenant_migration_live_and_dead_source(tmp_path_factory):
    """Two single-rank pools under one runs root: migrate a serving
    tenant live (export/import/release), then again with the source
    fenced dead — the post-SIGKILL recovery path."""
    runs_root = str(tmp_path_factory.mktemp("elasticroot"))
    run_a = os.path.join(runs_root, "pool-a")
    run_b = os.path.join(runs_root, "pool-b")
    os.makedirs(run_a)
    os.makedirs(run_b)
    saved = os.environ.get("NBD_RUN_DIR")
    gw_a = gw_b = None
    try:
        gw_a = _mini_pool(run_a)
        gw_b = _mini_pool(run_b)
        os.environ["NBD_RUN_DIR"] = saved or ""

        directory = router_mod.PoolDirectory(runs_root)
        assert sorted(directory.discover()) == [run_a, run_b]

        # ---- live path -------------------------------------------
        ta = attach(gw_a, "mig")
        tok = ta.token
        ta.serve_start(SPEC, max_batch=2, max_len=48, pad_to=4,
                       steps=2, queue_depth=8, inflight=8,
                       timeout=600)
        rid = ta.serve_submit(PROMPTS[0], MAX_NEW)["rid"]
        got = wait_results(ta, [rid], timeout=300)
        assert got[rid]["status"] == "completed", got
        ta.close()

        # place() must route AWAY from the loaded source pool.
        placed = directory.place(exclude=run_a)
        assert placed is not None and placed[0] == run_b

        out = router_mod.migrate_tenant("mig", run_a, run_b,
                                        force=True)
        assert out["status"] == "migrated", out
        assert out["src_alive"] and out["released"], out
        assert out["journal_moved"], out
        # The serving history is staged at the destination for its
        # serving plane to adopt on next start.
        assert os.path.exists(migrated_journal_path(run_b, "mig"))

        # The tenant reattaches at the DESTINATION with its original
        # token and epoch (ratcheted, never rewound), and can run.
        tb = attach(gw_b, "mig", token=tok)
        assert tb.token == tok
        assert tb.epoch >= out["epoch"] >= 1
        r = tb.execute("40 + 2", target_ranks=[0], timeout=180)
        assert (r.get("results") or {})["0"]["output"].strip() == "42"
        tb.close()
        # ...and the source no longer knows it.
        assert "mig" not in gw_a.registry.names()

        # ---- dead-source (post-SIGKILL) path ---------------------
        # The source's serving plane is still up (one per daemon);
        # the second tenant submits on it — its journal records are
        # interleaved with mig's, which is exactly what the filtered
        # export has to untangle.
        ta2 = attach(gw_a, "mig2")
        tok2 = ta2.token
        rid2 = ta2.serve_submit(PROMPTS[1], MAX_NEW)["rid"]
        got2 = wait_results(ta2, [rid2], timeout=300)
        assert got2[rid2]["status"] == "completed", got2
        ta2.close()

        # Kill the source pool, then restore its manifest with the
        # pid fenced to a corpse — exactly what the router sees after
        # the source daemon is SIGKILLed (its durable artifacts,
        # manifest + journal, survive on disk).  The daemon must be
        # DOWN first: a live daemon rewrites its manifest on tenant
        # churn and would race the fence.
        mpath = gateway_manifest_path(run_a)
        with open(mpath) as f:
            manifest = json.load(f)
        gw_a.close()                     # removes the manifest too
        manifest["pid"] = 2 ** 22 + 11   # nothing alive up there
        with open(mpath, "w") as f:
            json.dump(manifest, f)

        out2 = router_mod.migrate_tenant("mig2", run_a, run_b)
        assert out2["status"] == "migrated", out2
        assert not out2["src_alive"], out2
        assert not out2["released"], out2       # nothing to release
        assert out2["journal_moved"], out2

        tb2 = attach(gw_b, "mig2", token=tok2)
        assert tb2.token == tok2
        r = tb2.execute("'alive-at-b'", target_ranks=[0],
                        timeout=180)
        assert "alive-at-b" in (r.get("results") or {})["0"]["output"]
        tb2.close()
    finally:
        if saved is None:
            os.environ.pop("NBD_RUN_DIR", None)
        else:
            os.environ["NBD_RUN_DIR"] = saved
        for gw in (gw_b, gw_a):
            if gw is not None:
                try:
                    gw.close()
                except Exception:
                    pass


def test_autoscale_audit_flight_ring_and_postmortem(tmp_path_factory):
    """ISSUE 18: a pressure-driven autoscale grow leaves its FULL
    audit record — the pressure inputs, sustain clock, and verdict —
    on the daemon's flight ring, and a postmortem bundle captured
    afterwards carries it.  The `%dist_pool status --autoscale` ring
    (``decisions()``) holds the same records."""
    from nbdistributed_tpu.observability import postmortem as pm_mod
    from nbdistributed_tpu.resilience.autoscaler import AutoscalePolicy

    run_dir = str(tmp_path_factory.mktemp("autoscale_audit"))
    old = os.environ.get("NBD_RUN_DIR")
    os.environ["NBD_RUN_DIR"] = run_dir
    flightrec.reset_for_tests()
    gw = None
    threads = []
    try:
        gw = GatewayDaemon(
            2, backend="cpu",
            policy=SchedPolicy("fair", mesh_slots=1,
                               tenant_inflight=16, queue_depth=32),
            request_timeout=None, attach_timeout=240.0)
        t = attach(gw, "pressure")
        # Fast-cadence policy: queue pressure must sustain 1s, ticks
        # every 250ms, no idle shrink, long cooldown (one decision).
        gw.start_autoscale(AutoscalePolicy(
            min_workers=2, max_workers=3, interval_s=0.25,
            up_queue=2, up_backlog=10 ** 6, up_p95_s=0.0,
            sustain_s=1.0, idle_s=10 ** 6, cooldown_s=10 ** 6))

        def _cell():
            try:
                t.execute("import time as _t; _t.sleep(2.0)\n1",
                          timeout=240.0)
            except Exception:
                pass    # the epoch flip may retire a queued cell

        for _ in range(8):     # mesh_slots=1: 1 runs, 7 queue
            th = threading.Thread(target=_cell, daemon=True)
            th.start()
            threads.append(th)

        deadline = time.time() + 120.0
        while time.time() < deadline and gw.world_size != 3:
            time.sleep(0.5)
        assert gw.world_size == 3, \
            f"grow never fired: {gw._autoscaler.decisions()}"

        # The decisions() ring: the fired grow names its pressure
        # inputs and the sustain clock that armed it.
        grows = [r for r in gw._autoscaler.decisions()
                 if r["verdict"] == "grow"]
        assert grows, gw._autoscaler.decisions()
        g = grows[-1]
        assert g["target"] == 3 and not g["clamp"]
        assert any("queue" in s for s in g["pressure"]), g
        assert g["inputs"]["queued"] > 2 and g["sustain_s"] >= 1.0, g

        # The flight ring (the comm's "coordinator" ring — the one
        # postmortem recovers) holds the decision WITH its audit.
        gw.flight.flush()
        ring = flightrec.read_latest(run_dir, "coordinator")
        assert ring is not None
        decs = [e for e in ring["events"]
                if e.get("t") == "autoscale_decision"]
        assert decs, [e.get("t") for e in ring["events"]][-20:]
        audit = decs[-1].get("audit") or {}
        assert audit.get("verdict") == "grow", decs[-1]
        assert audit.get("inputs", {}).get("queued", 0) > 2, decs[-1]
        assert audit.get("pressure"), decs[-1]

        # And the postmortem bundle carries the same record.
        manifest = pm_mod.capture(gw.comm, [],
                                  reason="autoscale audit test")
        assert manifest is not None
        with open(os.path.join(manifest["dir"],
                               "flight_coordinator.json")) as f:
            bundle_ring = json.load(f)
        bdecs = [e for e in bundle_ring["events"]
                 if e.get("t") == "autoscale_decision"]
        assert bdecs and (bdecs[-1].get("audit") or {}).get("pressure")
        t.close(detach=True)
    finally:
        for th in threads:
            th.join(timeout=30)
        if gw is not None:
            try:
                gw.close()
            except Exception:
                pass
        if old is None:
            os.environ.pop("NBD_RUN_DIR", None)
        else:
            os.environ["NBD_RUN_DIR"] = old
