"""Acceptance test for the training integrity guard (ISSUE 19).

Three real worker subprocesses on the CPU backend train the same tiny
model independently with identical seeds — the replicated-params
invariant the replica-consistency audit exists to police.  A
``CorruptSpec`` on the runtime fault plan flips one bit of rank 1's
params mid-training (the deterministic stand-in for an SDC).  The
guard's audit at its step cadence must:

1. detect the divergence and NAME rank 1 as the minority,
2. repair it by re-broadcasting params + optimizer state from the
   majority root, and
3. leave every rank's final params **bit-identical** to a fault-free
   reference run of the same loop — the corruption leaves no trace.
"""

import ast
import json
import time

import pytest

from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
from nbdistributed_tpu.messaging import CommunicationManager

pytestmark = [pytest.mark.integration, pytest.mark.faults,
              pytest.mark.guard, pytest.mark.slow]

WORLD = 3
ATTACH_TIMEOUT = 180

# Executed once per worker: independent local-mesh training (each rank
# trains on its OWN device with the SAME seed, so params stay bitwise
# replicated across ranks), wrapped in a TrainGuard with a tight audit
# cadence.  ``_train`` leaves the finished guard in the namespace.
SETUP = """
import optax
from nbdistributed_tpu.parallel import data_parallel
from nbdistributed_tpu.parallel import mesh as mesh_mod
from nbdistributed_tpu.resilience import trainguard

def _build():
    m = mesh_mod.make_mesh({"dp": 1}, devices=jax.local_devices()[:1])
    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)
    k = jax.random.PRNGKey(0)
    params = {"w": jax.random.normal(k, (8, 4), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}
    opt = optax.adam(1e-2)
    p, _ = data_parallel.ddp_init(params, None, m)
    s = jax.jit(opt.init)(p)
    step = data_parallel.make_ddp_step(loss_fn, opt, m, guard=True)
    return step, p, s

def _train(steps):
    step, p, s = _build()
    g = trainguard.TrainGuard(step, p, s, audit_every=4,
                              snapshot_every=4, skip_budget=10,
                              checkpoint_every=0)
    kb = jax.random.PRNGKey(1)
    for _ in range(steps):
        kb, kx = jax.random.split(kb)
        x = jax.random.normal(kx, (16, 8), jnp.float32)
        y = jnp.zeros((16, 4), jnp.float32)
        g.step((x, y))
    g.finish()
    return g
"""

# Runs the loop and reports everything the assertions need as JSON.
REPORT = """
g = _train(12)
d = g.describe()
_mm = [dict(e) for e in g._events if e["kind"] == "mismatch"]
_res = {"fp": list(trainguard.tree_fingerprint(g.params)),
        "mismatches": d["mismatches"], "repairs": d["repairs"],
        "audits": d["audits"], "last_verdict": d["last_verdict"],
        "minority": _mm[0]["minority"] if _mm else None,
        "majority_rank": _mm[0]["majority_rank"] if _mm else None,
        "kinds": sorted({e["kind"] for e in g._events})}
import json as _json
_json.dumps(_res)
"""


def _results(responses):
    out = {}
    for r, m in responses.items():
        raw = m.data.get("output")
        assert raw, f"rank {r} produced no output: {m.data}"
        out[r] = json.loads(ast.literal_eval(raw))
    return out


def test_audit_detects_names_and_repairs_bit_flip():
    comm = CommunicationManager(num_workers=WORLD, timeout=60)
    pm = ProcessManager()
    pm.add_death_callback(lambda rank, rc: comm.mark_worker_dead(rank))
    try:
        pm.start_workers(WORLD, comm.port, backend="cpu")
        wait_until_ready(comm, pm, ATTACH_TIMEOUT)

        comm.send_to_all("execute", SETUP, timeout=120)

        # --- fault-free reference ------------------------------------
        ref = _results(comm.send_to_all("execute", REPORT, timeout=300))
        ref_fp = ref[0]["fp"]
        assert all(r["fp"] == ref_fp for r in ref.values()), \
            f"identical-seed training diverged without faults: {ref}"
        assert all(r["mismatches"] == 0 and r["repairs"] == 0
                   for r in ref.values()), ref

        # --- arm the SDC: one bit of rank 1's params at step 2 -------
        resp = comm.send_to_all(
            "chaos", {"action": "set",
                      "spec": {"seed": 7,
                               "corrupt": [{"rank": 1, "step": 2,
                                            "name": "w"}]}},
            timeout=60)
        assert all(m.data.get("status") == "armed"
                   for m in resp.values()), \
            {r: m.data for r, m in resp.items()}

        # --- chaos run -----------------------------------------------
        got = _results(comm.send_to_all("execute", REPORT, timeout=300))

        # every rank saw the SAME audit story: one mismatch naming
        # rank 1, repaired from majority root 0, later audits clean
        for r, res in got.items():
            assert res["mismatches"] == 1, (r, res)
            assert res["repairs"] == 1, (r, res)
            assert res["minority"] == [1], (r, res)
            assert res["majority_rank"] == 0, (r, res)
            assert res["last_verdict"] == "ok", (r, res)
            assert {"audit", "mismatch", "repair"} <= set(res["kinds"])

        # the injection actually fired, and only on rank 1
        assert "corrupt" in got[1]["kinds"]
        assert "corrupt" not in got[0]["kinds"]
        assert "corrupt" not in got[2]["kinds"]

        # repaired finals are bit-identical to the fault-free run
        for r, res in got.items():
            assert res["fp"] == ref_fp, \
                f"rank {r} final params differ from fault-free " \
                f"reference: {res['fp']} != {ref_fp}"

        # the guard heartbeat piggyback surfaced the repair
        st = comm.send_to_all("guard", {"action": "status"}, timeout=60)
        assert all(m.data.get("repairs") == 1 for m in st.values()), \
            {r: m.data.get("repairs") for r, m in st.items()}
    finally:
        try:
            comm.post(list(range(WORLD)), "shutdown")
            time.sleep(0.3)
        except Exception:
            pass
        pm.shutdown()
        comm.shutdown()
