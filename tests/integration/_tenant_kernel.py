"""Sacrificial tenant kernel for the gateway chaos integration test.

NOT a test module (no ``test_`` prefix).  Run as a subprocess:

    python tests/integration/_tenant_kernel.py RUN_DIR NAME OUT_JSON

Attaches to the gateway pool under RUN_DIR as tenant NAME, seeds a
double-execution tripwire (``a_hits = 0``), fires an in-flight cell
(bump ``a_hits``, sleep, yield it) WITHOUT waiting for the reply,
publishes its pid + tenant token to OUT_JSON, prints READY — then
ticks a seeded :class:`FaultPlan` (``NBD_FAULT_PLAN``) until it
SIGKILLs this process mid-cell: the notebook-kernel-crash half of the
tenant-isolation scenario, driven by the existing chaos machinery so
the kill point is deterministic.
"""

import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

# The in-flight cell: bumps the tripwire FIRST so a redelivered /
# double-executed cell is visible as a_hits > 1 after reattach.
CELL = ("a_hits += 1\n"
        "import time\n"
        "time.sleep(3.0)\n"
        "a_hits")


def main() -> int:
    run_dir, name, out_path = sys.argv[1], sys.argv[2], sys.argv[3]

    from nbdistributed_tpu.gateway.client import TenantClient
    from nbdistributed_tpu.gateway.daemon import read_gateway_manifest
    from nbdistributed_tpu.resilience.faults import FaultPlan

    m = read_gateway_manifest(run_dir)
    assert m, f"no gateway manifest under {run_dir}"
    plane = m["tenant_plane"]
    client = TenantClient(plane["host"], int(plane["port"]), name,
                          pool_token=m.get("pool_token"))
    client.execute("a_hits = 0", timeout=120)

    threading.Thread(target=lambda: client.execute(CELL, timeout=60),
                     daemon=True).start()

    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"pid": os.getpid(), "token": client.token,
                   "epoch": client.epoch}, f)
    os.replace(tmp, out_path)
    print("READY", flush=True)

    plan = FaultPlan.from_env()
    tick = 0
    while tick < 600:                     # hard stop: 60 s
        tick += 1
        if plan is not None and plan.should_kill(0, tick):
            os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(0.1)
    return 1                              # plan never fired — fail loud


if __name__ == "__main__":
    sys.exit(main())
