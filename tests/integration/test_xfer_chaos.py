"""Acceptance tests for the streaming bulk-transfer plane (ISSUE 20).

The scenario the tentpole exists for, end to end on the CPU backend:

1. A **sacrificial coordinator subprocess** brings up a fleet, starts
   a chunked push of a deterministic payload, delivers exactly the
   first half of the chunks, and is SIGKILLed mid-transfer by this
   test — ``%dist_push`` interrupted by a kernel crash.
2. The test process reattaches (``session.attach``), arms **8% seeded
   chunk drops + chunk corruption in BOTH directions** (coordinator
   plan for push frames, runtime chaos channel for worker reply
   frames), and re-runs the same push: the content-addressed xid must
   resume from the receivers' bitmaps (only missing chunks move),
   corrupted chunks must be refused by crc and re-sent (resent counter
   pinned), and every rank must apply the transfer **exactly once**.
3. The value is pulled back through the same chunked plane under the
   same chaos and must be **bit-identical**.
4. A repeat push moves zero bytes (completed-xid memo).

The fast variant (4 MB, 64 KiB chunks) runs in tier 1; the 256 MB
acceptance pin rides the ``slow`` lane and adds the memory half of the
credit-window bound: sender and receiver peak EXTRA rss during the
transfer is O(window x chunk), never O(payload).
"""

import json
import os
import resource
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from nbdistributed_tpu.messaging import xfer
from nbdistributed_tpu.observability import flightrec
from nbdistributed_tpu.resilience import FaultPlan, RetryPolicy, session

from _xfer_coord import PUSH_NAME, make_value

pytestmark = [pytest.mark.integration, pytest.mark.faults,
              pytest.mark.xfer]

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
XCOORD = os.path.join(REPO_ROOT, "tests", "integration",
                      "_xfer_coord.py")

# Aggressive redelivery: the run must make progress through 8% chunk
# loss without waiting out whole request deadlines.
RETRY = RetryPolicy(attempts=6, attempt_timeout_s=2.0,
                    backoff_base_s=0.1, backoff_max_s=0.5, jitter=0.25)


def _kill_manifest_pids(run_dir):
    m = session.read_manifest(run_dir) or {}
    for pid in (m.get("pids") or {}).values():
        try:
            os.kill(int(pid), signal.SIGKILL)
        except (OSError, ValueError):
            pass


def _vm_hwm_kb(pid: int) -> int:
    """Peak resident set of a live process, from /proc (Linux)."""
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    return 0


def _sigkill_resume_scenario(tmp_path, monkeypatch, *, world, nbytes,
                             csize, window, rss_bounds=False):
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    monkeypatch.setenv("NBD_RUN_DIR", run_dir)
    monkeypatch.setenv("NBD_XFER_CHUNK_BYTES", str(csize))
    monkeypatch.setenv("NBD_XFER_WINDOW", str(window))
    # Pulls of the test payload must ride the chunked plane, not the
    # inline fast path.
    monkeypatch.setenv("NBD_XFER_THRESHOLD_BYTES", str(1 << 20))
    flightrec.reset_for_tests()

    coord1 = subprocess.Popen(
        [sys.executable, XCOORD, run_dir, str(world), str(nbytes),
         str(csize)],
        cwd=REPO_ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    comm = pm = None
    try:
        # --- phase 1: half the chunks land, then the coordinator dies
        status_path = os.path.join(run_dir, "xcoord.json")
        deadline = time.time() + 300
        while not os.path.exists(status_path):
            assert coord1.poll() is None, (
                "coordinator #1 died during bring-up:\n"
                + coord1.stdout.read().decode("utf-8", "replace"))
            assert time.time() < deadline, "coordinator #1 never ready"
            time.sleep(0.2)
        st = json.load(open(status_path))
        n, half = st["n_chunks"], st["half"]
        assert half >= 2, f"payload too small to interrupt: {st}"
        os.kill(coord1.pid, signal.SIGKILL)  # mid-%dist_push
        coord1.wait()

        # --- phase 2: reattach, arm chaos BOTH directions ------------
        comm, pm, manifest, hello = session.attach(
            run_dir, attach_timeout=120, request_timeout=120,
            retry=RETRY)
        assert comm.session_epoch == 2
        assert sorted(hello) == list(range(world))
        # Coordinator plan: drops + bit-flips on outgoing xfer_chunk
        # frames (the push direction).
        comm.set_fault_plan(FaultPlan(seed=99, xfer_drop=0.08,
                                      xfer_corrupt=0.08))
        # Worker plan via the runtime chaos channel: drops + bit-flips
        # on bulk (>= 64 KiB) reply frames (the pull direction).
        resp = comm.send_to_all(
            "chaos", {"action": "set",
                      "spec": {"seed": 55, "xfer_drop": 0.08,
                               "xfer_corrupt": 0.08}}, timeout=60)
        assert all((m.data or {}).get("status") == "armed"
                   for m in resp.values()), \
            {r: m.data for r, m in resp.items()}

        value = make_value(nbytes)
        if rss_bounds:
            worker_hwm0 = {r: _vm_hwm_kb(p.pid)
                           for r, p in pm.processes.items()}
            rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss

        # --- phase 3: the SAME push resumes under chaos --------------
        stats = xfer.push_value(comm, list(range(world)), PUSH_NAME,
                                value)
        assert stats["xid"] == st["xid"], \
            "content-addressed xid changed across coordinator " \
            "generations — resume impossible"
        assert stats["chunks"] == n
        # Only the missing half moved: every rank's bitmap held the
        # first-generation chunks.
        assert stats["resumed_chunks"] == world * half, stats
        # Chaos was real and healed chunk-by-chunk, never whole-payload.
        assert stats["resent_chunks"] >= 1, \
            f"seeded chaos produced no resends: {stats}"
        # Exactly-once bind on every rank, both from the push's own
        # accounting and the workers' counters.
        assert stats["already_done"] == []
        assert stats["applies"] == {r: 1 for r in range(world)}, stats
        gs = comm.send_to_all("get_status", timeout=60)
        for r, m in gs.items():
            xs = m.data["xfer"]
            assert xs["applies"] == 1, (r, xs)
            assert xs["crc_rejects"] + xs["dup_chunks"] >= 0  # present
        # Deterministic half of the credit-window memory bound.
        assert stats["inflight_peak_bytes"] <= window * csize, stats

        if rss_bounds:
            # Sender: peak EXTRA memory during the push is O(window x
            # chunk) + codec transients — nowhere near a second copy
            # of the payload (the legacy single-frame path allocated
            # 2-3x payload here).
            rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            sender_extra = (rss1 - rss0) * 1024
            assert sender_extra < min(nbytes // 2, 96 << 20), \
                (f"sender extra rss {sender_extra / 1e6:.0f} MB is not "
                 f"credit-window-bounded (window x chunk = "
                 f"{window * csize / 1e6:.0f} MB)")
            # Receiver: destination arrays (payload-sized, expected)
            # plus window-bounded transients — never frame + decode
            # copy + value at once.
            for r, p in pm.processes.items():
                extra = (_vm_hwm_kb(p.pid) - worker_hwm0[r]) * 1024
                assert extra < nbytes + (96 << 20), \
                    (f"rank {r} extra rss {extra / 1e6:.0f} MB exceeds "
                     f"payload + window bound")

        # --- phase 4: pull back under the same chaos, bit-identical --
        pull_resent = 0
        for r in range(world):
            got, pstats = xfer.pull_value(comm, r, PUSH_NAME)
            assert pstats["chunks"] == n and not pstats["inline"]
            assert pstats["inflight_peak_bytes"] <= window * csize
            pull_resent += pstats["resent_chunks"]
            assert got["w"].dtype == value["w"].dtype
            assert np.array_equal(got["w"], value["w"]), \
                f"rank {r} pull is not bit-identical after chaos"
            del got
        assert pull_resent >= 1, \
            "worker-side chunk corruption produced no pull resends"

        # --- phase 5: a repeat push moves nothing --------------------
        again = xfer.push_value(comm, list(range(world)), PUSH_NAME,
                                value)
        assert again["xid"] == stats["xid"]
        assert again["already_done"] == list(range(world))
        assert again["wire_bytes"] == 0 and again["applies"] == {}
        gs = comm.send_to_all("get_status", timeout=60)
        for r, m in gs.items():
            assert m.data["xfer"]["applies"] == 1, \
                f"rank {r} double-applied: {m.data['xfer']}"
        return stats
    finally:
        if coord1.poll() is None:
            coord1.kill()
        if comm is not None:
            try:
                comm.post(list(range(world)), "shutdown")
                time.sleep(0.3)
            except Exception:
                pass
            comm.shutdown()
        if pm is not None:
            pm.shutdown()
        _kill_manifest_pids(run_dir)
        flightrec.reset_for_tests()


def test_push_sigkill_resume_chaos_fast(tmp_path, monkeypatch):
    """Tier-1 variant: 4 MB payload, 64 KiB chunks, 2 ranks."""
    _sigkill_resume_scenario(tmp_path, monkeypatch, world=2,
                             nbytes=4 << 20, csize=1 << 16, window=4)


@pytest.mark.slow
def test_push_sigkill_resume_chaos_256mb(tmp_path, monkeypatch):
    """The acceptance pin: 256 MB through SIGKILL + 8% two-way chaos,
    with the rss half of the credit-window memory bound asserted."""
    _sigkill_resume_scenario(tmp_path, monkeypatch, world=1,
                             nbytes=256 << 20, csize=1 << 20, window=4,
                             rss_bounds=True)
