"""Acceptance test for the collective hang watchdog + stuck-cell
doctor (ISSUE 5), against real worker subprocesses on the CPU backend:

1. a uniformly-slow cell (every rank equally busy, no divergence)
   produces ZERO hang verdicts — slow is not hung;
2. the chaos plan freezes rank 1 inside its second collective entry
   (deterministic ``freeze_rank``/``freeze_at``) while rank 0 finishes
   the cell: the watchdog flags the cell HUNG with a **skew** verdict
   naming rank 1 and the divergent collective, `%dist_doctor`'s report
   names the laggard, a mid-hang postmortem bundle carries the hang
   report, and the escalation ladder (warn → stack-dump → interrupt)
   breaks the hang WITHOUT killing any rank;
3. a pure-Python infinite loop on rank 1 (zero collectives) is flagged
   **stall**, not skew, and the ladder breaks it the same way;
4. the mesh survives it all: a cross-process all_reduce still works.
"""

import json
import os
import threading
import time

import pytest

from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
from nbdistributed_tpu.messaging import CommunicationManager
from nbdistributed_tpu.observability import flightrec
from nbdistributed_tpu.observability import metrics as obs_metrics
from nbdistributed_tpu.observability import postmortem as pm_mod
from nbdistributed_tpu.resilience import (HangPolicy, HangWatchdog,
                                          hang_report)

pytestmark = [pytest.mark.integration, pytest.mark.hang]

WORLD = 2
ATTACH_TIMEOUT = 120

HANG_CELL = """
import jax.numpy as jnp
a = all_reduce(jnp.ones(2))        # collective #1: both ranks join
if rank == 1:
    b = all_reduce(a)              # collective #2: frozen by the plan
'done-%d' % rank
"""

LOOP_CELL = """
if rank == 1:
    while True:                    # data-dependent infinite loop
        pass
'ok-%d' % rank
"""


def _bring_up(extra_env=None):
    comm = CommunicationManager(num_workers=WORLD, timeout=120)
    pm = ProcessManager()
    pm.add_death_callback(lambda rank, rc: comm.mark_worker_dead(rank))
    try:
        pm.start_workers(WORLD, comm.port, backend="cpu",
                         extra_env=extra_env)
        wait_until_ready(comm, pm, ATTACH_TIMEOUT)
    except Exception:
        pm.shutdown()
        comm.shutdown()
        raise
    return comm, pm


def _send_async(comm, code, timeout=120):
    out = {}

    def _run():
        try:
            out["resp"] = comm.send_to_all(
                "execute", {"code": code, "target_ranks": [0, 1]},
                timeout=timeout)
        except Exception as e:  # pragma: no cover - surfaced by asserts
            out["error"] = e

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    return t, out


def _wait_active_hang(wd, deadline_s=60):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        st = wd.status()
        if st["active"]:
            return st
        time.sleep(0.2)
    pytest.fail(f"watchdog never flagged a hang: {wd.status()}")


def _run_cell(comm, code, timeout=120):
    return {r: m.data for r, m in comm.send_to_all(
        "execute", {"code": code, "target_ranks": [0, 1]},
        timeout=timeout).items()}


def test_hang_watchdog_detects_diagnoses_and_breaks(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("NBD_RUN_DIR", str(tmp_path / "run"))
    flightrec.reset_for_tests()
    # Deterministic wedge: rank 1 blocks inside its SECOND collective
    # entry (the hang cell's in-branch all_reduce), one-shot.
    env = {"NBD_FAULT_PLAN": json.dumps(
        {"freeze_rank": 1, "freeze_at": 2, "freeze_s": 600})}
    comm, pm = _bring_up(extra_env=env)
    wd = HangWatchdog(HangPolicy(
        poll_s=0.25, skew_s=3.0, stall_s=8.0, grace_s=1.0,
        escalate=("warn", "dump", "interrupt")))
    wd.attach(comm, pm)
    try:
        # --- phase 1: uniformly slow is NOT hung ---------------------
        out = _run_cell(comm, "import time\ntime.sleep(4)\n'slow-ok'")
        assert all(d.get("output") == "'slow-ok'" for d in out.values())
        assert wd.cells_flagged == 0, wd.status()

        # --- phase 2: rank 1 freezes mid-collective ------------------
        t, box = _send_async(comm, HANG_CELL)
        st = _wait_active_hang(wd)
        (active,) = st["active"].values()
        assert active["kind"] == "skew", st
        assert active["ranks"] == [1], st
        (verdict,) = [v for v in st["last_verdicts"]
                      if v["kind"] == "skew"]
        # The divergence point: rank 1 is wedged inside all_reduce #2.
        assert verdict["op"] == "all_reduce" and verdict["seq"] == 2
        assert verdict["peers"] == [0]  # rank 0 finished the cell

        # The stuck-cell doctor, consulted MID-HANG, names the
        # laggard and the divergence without touching the wedged
        # rank's request loop.
        report = hang_report(comm, pm, wd, dump_stacks=False)
        assert "HUNG [skew]" in report
        assert "rank(s) [1]" in report
        assert "all_reduce" in report and "#2" in report
        # A postmortem captured mid-hang bundles the diagnosis.
        manifest = pm_mod.capture(comm, [], reason="mid-hang",
                                  hang_report=report)
        assert manifest is not None
        assert manifest.get("hang_report") == "hang_report.txt"
        bundled = open(os.path.join(manifest["dir"],
                                    "hang_report.txt")).read()
        assert "HUNG [skew]" in bundled

        # The escalation ladder breaks the hang: the frozen rank's
        # cell aborts with KeyboardInterrupt, rank 0's result stands,
        # and NOBODY dies.
        t.join(timeout=90)
        assert not t.is_alive(), "escalation never broke the hang"
        assert "error" not in box, box
        resp = {r: m.data for r, m in box["resp"].items()}
        assert resp[0].get("output") == "'done-0'", resp
        assert "KeyboardInterrupt" in (resp[1].get("error") or ""), resp
        assert pm.alive_ranks() == [0, 1]
        esc = wd.escalations
        assert esc.get("warn", 0) >= 1 and esc.get("dump", 0) >= 1 \
            and esc.get("interrupt", 0) >= 1, esc
        # The dump step's SIGUSR1 left per-rank all-thread stacks
        # (per-pid file names, like the flight rings, so a later heal
        # can never truncate this evidence).
        from nbdistributed_tpu.resilience.watchdog import _stack_file
        stacks = _stack_file(os.environ["NBD_RUN_DIR"], 1)
        assert stacks is not None and os.path.exists(stacks)
        assert "File" in open(stacks).read()
        # Metrics counted the verdict and every ladder step.
        counters = obs_metrics.registry().to_json()["counters"]
        assert counters.get('nbd_hang_verdicts_total{kind="skew"}',
                            0) >= 1
        assert counters.get('nbd_hang_escalations_total'
                            '{step="interrupt"}', 0) >= 1

        # Hang resolved: active set drains.
        deadline = time.time() + 15
        while wd.status()["active"] and time.time() < deadline:
            time.sleep(0.2)
        assert wd.status()["active"] == {}
        assert wd.cells_resolved >= 1

        # --- phase 3: infinite loop, zero collectives => STALL -------
        flagged_before = wd.cells_flagged
        t, box = _send_async(comm, LOOP_CELL)
        st = _wait_active_hang(wd)
        (active,) = st["active"].values()
        assert active["kind"] == "stall", st
        assert active["ranks"] == [1], st
        t.join(timeout=90)
        assert not t.is_alive(), "escalation never broke the loop"
        resp = {r: m.data for r, m in box["resp"].items()}
        assert "KeyboardInterrupt" in (resp[1].get("error") or ""), resp
        assert wd.cells_flagged == flagged_before + 1
        assert pm.alive_ranks() == [0, 1]
        # Let the stall verdict drain (the busy ping persists until
        # the next idle heartbeat arrives) before the healthy-mesh
        # phase asserts a clean doctor report.
        deadline = time.time() + 15
        while wd.status()["active"] and time.time() < deadline:
            time.sleep(0.2)
        assert wd.status()["active"] == {}

        # --- phase 4: the mesh SURVIVED both hangs -------------------
        # (the freeze was one-shot; collectives run clean again).  A
        # late-landing interrupt may abort one follow-up cell — absorb
        # it with one retry, like %dist_interrupt's probe does.
        for attempt in range(3):
            out = _run_cell(
                comm, "import jax.numpy as jnp\n"
                      "float(all_reduce(jnp.ones(2))[0])")
            if all("error" not in d for d in out.values()):
                break
            assert all("KeyboardInterrupt" in d.get("error", "")
                       for d in out.values() if "error" in d), out
        assert {d.get("output") for d in out.values()} == {"2.0"}, out
        # Doctor on a healthy mesh: no verdicts, stacks readable.
        report = hang_report(comm, pm, wd, dump_stacks=True,
                             stack_wait_s=1.0)
        assert "verdicts: none" in report
        assert "stacks (SIGUSR1" in report
    finally:
        wd.stop()
        try:
            comm.post(list(range(WORLD)), "shutdown")
            time.sleep(0.3)
        except Exception:
            pass
        pm.shutdown()
        comm.shutdown()
