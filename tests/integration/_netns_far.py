"""Far-side helper for the netns scenario: runs inside its OWN network
namespace (spawned as ``unshare -n python _netns_far.py <workdir>``).

Protocol with the orchestrator (_netns_world.py), via files in the
shared workdir:

1. write ``far.pid`` (the orchestrator moves the veth peer into our
   namespace by this pid);
2. wait for ``vethB`` to appear, bring it + lo up with 10.99.0.2/24;
3. start a :class:`HostAgent` on 10.99.0.2 and write ``far.ready``;
4. serve until ``stop`` appears.
"""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from nbdistributed_tpu.manager.hostagent import HostAgent  # noqa: E402

FAR_ADDR = "10.99.0.2"
AGENT_PORT = 7411
TOKEN = "netns-secret"


def sh(*cmd) -> int:
    return subprocess.run(list(cmd), capture_output=True).returncode


def main() -> int:
    workdir = sys.argv[1]
    with open(os.path.join(workdir, "far.pid"), "w") as f:
        f.write(str(os.getpid()))
    deadline = time.time() + 60
    while sh("ip", "link", "show", "vethB") != 0:
        if time.time() > deadline:
            print("far: vethB never arrived", flush=True)
            return 1
        time.sleep(0.1)
    assert sh("ip", "link", "set", "lo", "up") == 0
    assert sh("ip", "addr", "add", f"{FAR_ADDR}/24", "dev", "vethB") == 0
    assert sh("ip", "link", "set", "vethB", "up") == 0

    run_dir = os.path.join(workdir, "run_far")
    os.makedirs(run_dir, exist_ok=True)
    os.environ["NBD_RUN_DIR"] = run_dir
    agent = HostAgent(FAR_ADDR, AGENT_PORT, auth_token=TOKEN,
                      host_label="hostB", run_dir=run_dir)
    with open(os.path.join(workdir, "far.ready"), "w") as f:
        f.write(f"{agent.host}:{agent.port}")
    stop = os.path.join(workdir, "stop")
    try:
        while not os.path.exists(stop):
            time.sleep(0.2)
    finally:
        agent.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
