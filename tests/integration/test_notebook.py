"""Notebook-level integration: execute the demo notebook through a real
Jupyter kernel with nbclient and assert on the streamed, rank-tagged
outputs — the test tier the reference only declared in packaging
(reference: pyproject.toml:36-42 lists nbformat+nbclient; SURVEY §4).
"""

import os

import pytest

pytestmark = [pytest.mark.integration, pytest.mark.slow]

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
NOTEBOOK = os.path.join(REPO_ROOT, "examples", "00_quickstart.ipynb")


def _all_text(nb):
    chunks = []
    for cell in nb.cells:
        for out in cell.get("outputs", []):
            if out.get("output_type") == "stream":
                chunks.append(out.get("text", ""))
            elif out.get("output_type") == "execute_result":
                chunks.append(out.get("data", {}).get("text/plain", ""))
            elif out.get("output_type") == "error":
                chunks.append("\n".join(out.get("traceback", [])))
    return "\n".join(chunks)


def _assert_clean(nb):
    errors = [out for cell in nb.cells
              for out in cell.get("outputs", [])
              if out.get("output_type") == "error"]
    assert not errors, errors


def _execute_notebook(filename: str, *, timeout: int,
                      env_patch: dict | None = None):
    """Run one example notebook through a real Jupyter kernel with the
    repo on PYTHONPATH (kernel + its spawned workers must import this
    checkout); env is patched for the duration and restored."""
    nbclient = pytest.importorskip("nbclient")
    import nbformat

    nb = nbformat.read(os.path.join(REPO_ROOT, "examples", filename),
                       as_version=4)
    env_patch = dict(env_patch or {})
    env_patch["PYTHONPATH"] = (REPO_ROOT + os.pathsep
                               + os.environ.get("PYTHONPATH", ""))
    old = {k: os.environ.get(k) for k in env_patch}
    os.environ.update(env_patch)
    try:
        client = nbclient.NotebookClient(
            nb, timeout=timeout, kernel_name="python3",
            resources={"metadata": {"path": REPO_ROOT}})
        client.execute()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return nb


@pytest.fixture(scope="module")
def executed_nb():
    return _execute_notebook(
        "00_quickstart.ipynb", timeout=300,
        env_patch={"NBD_NOTEBOOK_BACKEND": "cpu",
                   "NBD_NOTEBOOK_WORKERS": "2"})


def test_notebook_runs_clean(executed_nb):
    _assert_clean(executed_nb)


def test_notebook_rank_tagged_output(executed_nb):
    text = _all_text(executed_nb)
    assert "Rank 0" in text and "Rank 1" in text


def test_notebook_collective_result(executed_nb):
    # all_reduce of ones*(rank+1) over 2 ranks -> 3.0 on every rank.
    assert "3.0" in _all_text(executed_nb)


def test_notebook_training_progresses(executed_nb):
    text = _all_text(executed_nb)
    assert "step 0: loss" in text and "step 4: loss" in text
    assert "eval loss" in text


def test_notebook_broadcast_matches(executed_nb):
    # The cell after the %%rank[0] creation echoes W.sum() per rank;
    # both ranks must show the identical value.
    import re

    assert "created on rank 0 only" in _all_text(executed_nb)
    cell = next(c for c in executed_nb.cells
                if c.cell_type == "code" and "broadcast(W" in c.source)
    text = "\n".join(o.get("text", "") for o in cell["outputs"])
    sums = re.findall(r"Rank (\d):\s*\n(-?\d+\.\d+)", text)
    assert sorted(r for r, _ in sums) == ["0", "1"], text
    assert len({v for _, v in sums}) == 1, text


def test_notebook_no_worker_errors(executed_nb):
    text = _all_text(executed_nb)
    assert "❌" not in text and "Traceback" not in text, text[-2000:]


def test_notebook_checkpoint_restore_exact(executed_nb):
    text = _all_text(executed_nb)
    assert "ranks saved" in text and "ranks restored" in text
    assert "(exact)" in text


@pytest.fixture(scope="module")
def executed_parallelism_nb():
    # The notebook forces its own cpu/8-device env internally.
    return _execute_notebook("01_parallelism.ipynb", timeout=600)


def test_parallelism_notebook_runs_clean(executed_parallelism_nb):
    _assert_clean(executed_parallelism_nb)


def test_parallelism_notebook_strategies_exact(executed_parallelism_nb):
    text = _all_text(executed_parallelism_nb)
    assert "ring" in text and "ulysses" in text
    assert "pipeline max |err|" in text
    assert "MoE loss over dp×ep mesh" in text
    assert "moment sharding" in text and "dp" in text
    assert "greedy:" in text and "top-k/p:" in text
    assert "ring-attention train step over dp×sp×tp" in text
    assert "int8 vs bf16 top-1 agreement" in text
    assert "LoRA:" in text and "adapter params" in text
    assert "FSDP train step: loss" in text and "sharded 4-way" in text
    assert "speculative == target greedy: True" in text
    assert "self-draft mean accepted/round: 3.00" in text
    assert "batched speculative (B=2) == batched greedy: True" in text
    assert "1F1B vs GPipe grads match: True" in text
    assert "buffer 7 deep" in text
    assert "sparse MoE dispatch == dense: True" in text
    assert "3/8 hops pay compute+ppermute" in text


@pytest.fixture(scope="module")
def executed_finetune_nb(tmp_path_factory):
    """The reference's flagship journey (00_accelerate.ipynb): local
    SmolLM2-135M-architecture checkpoint -> load_hf_pretrained ->
    packed local-text dataset -> cell-by-cell DDP fine-tune ->
    generation.  (Checkpoint is locally constructed: zero-egress
    environment, see BASELINE.md.)  Per-run temp dirs: no /tmp litter
    or cross-run races on the ~0.5G checkpoint."""
    tmp = tmp_path_factory.mktemp("finetune_nb")
    return _execute_notebook(
        "02_finetune.ipynb", timeout=600,
        env_patch={"NBD_NOTEBOOK_BACKEND": "cpu",
                   "NBD_NOTEBOOK_WORKERS": "2",
                   "NBD_NOTEBOOK_CKPT_DIR": str(tmp / "ckpt"),
                   "NBD_NOTEBOOK_CK_OUT": str(tmp / "ck_out")})


def test_finetune_notebook_runs_clean(executed_finetune_nb):
    _assert_clean(executed_finetune_nb)


def test_finetune_notebook_journey(executed_finetune_nb):
    """The full accelerate-style journey, rank-tagged: checkpoint
    built, loaded on both ranks, real-text dataset packed, DDP loss
    improves, generation produced, state checkpointed."""
    text = _all_text(executed_finetune_nb)
    assert "SmolLM2-135M-architecture" in text
    # 134.5M torch params; the tied lm_head materializes as embed.T in
    # the JAX pytree -> 162.8M leaves.
    assert "loaded 162.8M params, d_model=576, layers=30" in text
    assert "Rank 0" in text and "Rank 1" in text
    assert "step 0: loss" in text and "step 3: loss" in text
    assert "improved" in text and "NOT improved" not in text
    assert "continuation" in text
    assert "ranks saved" in text
    assert "❌" not in text
