"""Notebook-level integration: execute the demo notebook through a real
Jupyter kernel with nbclient and assert on the streamed, rank-tagged
outputs — the test tier the reference only declared in packaging
(reference: pyproject.toml:36-42 lists nbformat+nbclient; SURVEY §4).
"""

import os

import pytest

pytestmark = [pytest.mark.integration, pytest.mark.slow]

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
NOTEBOOK = os.path.join(REPO_ROOT, "examples", "00_quickstart.ipynb")


def _all_text(nb):
    chunks = []
    for cell in nb.cells:
        for out in cell.get("outputs", []):
            if out.get("output_type") == "stream":
                chunks.append(out.get("text", ""))
            elif out.get("output_type") == "execute_result":
                chunks.append(out.get("data", {}).get("text/plain", ""))
            elif out.get("output_type") == "error":
                chunks.append("\n".join(out.get("traceback", [])))
    return "\n".join(chunks)


@pytest.fixture(scope="module")
def executed_nb():
    nbclient = pytest.importorskip("nbclient")
    import nbformat

    nb = nbformat.read(NOTEBOOK, as_version=4)
    env_patch = {
        "NBD_NOTEBOOK_BACKEND": "cpu",
        "NBD_NOTEBOOK_WORKERS": "2",
        # Kernel + its workers must import the repo checkout.
        "PYTHONPATH": REPO_ROOT + os.pathsep +
        os.environ.get("PYTHONPATH", ""),
    }
    old = {k: os.environ.get(k) for k in env_patch}
    os.environ.update(env_patch)
    try:
        client = nbclient.NotebookClient(
            nb, timeout=300, kernel_name="python3",
            resources={"metadata": {"path": REPO_ROOT}})
        client.execute()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return nb


def test_notebook_runs_clean(executed_nb):
    errors = [out for cell in executed_nb.cells
              for out in cell.get("outputs", [])
              if out.get("output_type") == "error"]
    assert not errors, errors


def test_notebook_rank_tagged_output(executed_nb):
    text = _all_text(executed_nb)
    assert "Rank 0" in text and "Rank 1" in text


def test_notebook_collective_result(executed_nb):
    # all_reduce of ones*(rank+1) over 2 ranks -> 3.0 on every rank.
    assert "3.0" in _all_text(executed_nb)


def test_notebook_training_progresses(executed_nb):
    text = _all_text(executed_nb)
    assert "step 0: loss" in text and "step 4: loss" in text
    assert "eval loss" in text


def test_notebook_broadcast_matches(executed_nb):
    # The cell after the %%rank[0] creation echoes W.sum() per rank;
    # both ranks must show the identical value.
    import re

    assert "created on rank 0 only" in _all_text(executed_nb)
    cell = next(c for c in executed_nb.cells
                if c.cell_type == "code" and "broadcast(W" in c.source)
    text = "\n".join(o.get("text", "") for o in cell["outputs"])
    sums = re.findall(r"Rank (\d):\s*\n(-?\d+\.\d+)", text)
    assert sorted(r for r, _ in sums) == ["0", "1"], text
    assert len({v for _, v in sums}) == 1, text


def test_notebook_no_worker_errors(executed_nb):
    text = _all_text(executed_nb)
    assert "❌" not in text and "Traceback" not in text, text[-2000:]


def test_notebook_checkpoint_restore_exact(executed_nb):
    text = _all_text(executed_nb)
    assert "ranks saved" in text and "ranks restored" in text
    assert "(exact)" in text


@pytest.fixture(scope="module")
def executed_parallelism_nb():
    nbclient = pytest.importorskip("nbclient")
    import nbformat

    path = os.path.join(REPO_ROOT, "examples", "01_parallelism.ipynb")
    nb = nbformat.read(path, as_version=4)
    # Kernel must import the repo checkout (same contract as
    # executed_nb above); the notebook forces its own cpu/8-device env.
    env_patch = {"PYTHONPATH": REPO_ROOT + os.pathsep +
                 os.environ.get("PYTHONPATH", "")}
    old = {k: os.environ.get(k) for k in env_patch}
    os.environ.update(env_patch)
    try:
        client = nbclient.NotebookClient(
            nb, timeout=600, kernel_name="python3",
            resources={"metadata": {"path": REPO_ROOT}})
        client.execute()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return nb


def test_parallelism_notebook_runs_clean(executed_parallelism_nb):
    errors = [out for cell in executed_parallelism_nb.cells
              for out in cell.get("outputs", [])
              if out.get("output_type") == "error"]
    assert not errors, errors


def test_parallelism_notebook_strategies_exact(executed_parallelism_nb):
    text = _all_text(executed_parallelism_nb)
    assert "ring" in text and "ulysses" in text
    assert "pipeline max |err|" in text
    assert "MoE loss over dp×ep mesh" in text
    assert "moment sharding" in text and "dp" in text
    assert "greedy:" in text and "top-k/p:" in text
    assert "ring-attention train step over dp×sp×tp" in text
    assert "int8 vs bf16 top-1 agreement" in text
    assert "LoRA:" in text and "adapter params" in text
    assert "FSDP train step: loss" in text and "sharded 4-way" in text
    assert "speculative == target greedy: True" in text
    assert "self-draft mean accepted/round: 3.00" in text
