"""Serving fast path under chaos (ISSUE 17), end to end on the CPU
backend: the closed-loop load generator drives a PAGED, MULTI-RANK
decode plane at roughly twice its measured sustainable rate while a
decode rank is SIGKILLed mid-run and the survivors drop 8% of
control-plane frames.

The contract under test:

1. **Exactly-once under overload + faults**: every ACCEPTED request
   reaches a terminal verdict exactly once — completed requests carry
   their EXACT solo-``generate`` greedy streams (journal-replay
   re-admission across the failover is bit-identical), everything
   else carries an explicit shed/rejected verdict.  Zero hung
   requests, zero silent drops (the loadgen report's conservation
   check is the arbiter).
2. **Multi-rank decode actually uses the slice**: more than one rank
   takes placements (per-rank ``ranks`` telemetry from
   ``serve_status``), and per-rank KV-block occupancy reaches the
   pool-status heartbeat surface.
3. **Chunked prefill bounds TPOT**: a long prompt streams in chunks
   between decode ticks, so an active short stream keeps emitting
   while the long prompt prefills — and both streams stay bit-exact.

Marked ``slow`` on purpose (pool spin-up); the CI resilience job owns
these (marker ``serve``).  ``test_loadgen_smoke_two_ranks`` is the
~15s CI smoke; the chaos scenario is the full drill.
"""

import ast
import time

import pytest

from nbdistributed_tpu.gateway.client import TenantClient
from nbdistributed_tpu.gateway.daemon import GatewayDaemon
from nbdistributed_tpu.gateway.scheduler import SchedPolicy
from nbdistributed_tpu.observability import flightrec
from nbdistributed_tpu.resilience.faults import FaultPlan
from nbdistributed_tpu.serving_fast import LoadConfig, run_load, \
    synth_schedule, validate_report
from nbdistributed_tpu.serving_fast.loadgen import ClientTransport

pytestmark = [pytest.mark.integration, pytest.mark.serve,
              pytest.mark.gateway, pytest.mark.faults,
              pytest.mark.slow]

WORLD = 3

SPEC = (
    "import jax as _j, jax.numpy as _jn\n"
    "from nbdistributed_tpu.models import tiny_config, init_params\n"
    "cfg = tiny_config(dtype=_jn.float32, use_flash=False)\n"
    "params = init_params(_j.random.PRNGKey(0), cfg)\n")


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    import os
    run_dir = str(tmp_path_factory.mktemp("servefast"))
    old = {k: os.environ.get(k)
           for k in ("NBD_RUN_DIR", "NBD_RETRY_TIMEOUT_S",
                     "NBD_RETRY_ATTEMPTS")}
    os.environ["NBD_RUN_DIR"] = run_dir
    # Retry layer ON: the 8%-drop phase leans on same-msg-id
    # redelivery + the worker replay cache.
    os.environ["NBD_RETRY_TIMEOUT_S"] = "5"
    os.environ["NBD_RETRY_ATTEMPTS"] = "6"
    flightrec.reset_for_tests()
    gw = GatewayDaemon(
        WORLD, backend="cpu",
        policy=SchedPolicy("fair", mesh_slots=1, tenant_inflight=16,
                           queue_depth=32),
        request_timeout=None, attach_timeout=240.0)
    try:
        yield gw
    finally:
        gw.close()
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def attach(pool, name, **kw):
    return TenantClient(pool.tenant_host, pool.tenant_port, name,
                        pool_token=pool.pool_token, **kw)


def solo_refs(client, jobs) -> list[list[int]]:
    """Solo ``generate`` references for ``[(prompt, max_new)]``,
    computed ON rank 0 (same process family as the decode ranks, so
    the equality check cannot hinge on XLA flag differences)."""
    cell = (
        "import jax as _j, jax.numpy as _jn, numpy as _np\n"
        "from nbdistributed_tpu.models import (tiny_config, "
        "init_params, generate)\n"
        "_cfg = tiny_config(dtype=_jn.float32, use_flash=False)\n"
        "_p = init_params(_j.random.PRNGKey(0), _cfg)\n"
        f"_jobs = {jobs!r}\n"
        "[[int(t) for t in _np.asarray(generate(_p, _jn.asarray(pr, "
        "_jn.int32)[None], _cfg, n))[0][len(pr):]] "
        "for pr, n in _jobs]")
    out = client.execute(cell, target_ranks=[0], timeout=600)
    results = out.get("results") or {}
    assert "0" in results, out
    return ast.literal_eval(results["0"].get("output"))


def assert_completed_bit_identical(client, cfg, report):
    plan = synth_schedule(cfg)      # deterministic: same cfg = same plan
    comp = [r for r in (report.get("requests") or ())
            if r["status"] == "completed"]
    assert comp, f"no completed requests to check: {report}"
    jobs = [(plan[r["i"]]["prompt"], plan[r["i"]]["max_new"])
            for r in comp]
    refs = solo_refs(client, jobs)
    for r, ref in zip(comp, refs):
        assert r["tokens"] == ref, \
            (f"request {r['rid']} (plan item {r['i']}): "
             f"{r['tokens']} != solo {ref}")


def wait_result(client, rid, timeout=240.0) -> dict:
    deadline = time.time() + timeout
    while time.time() < deadline:
        r = client.serve_result(rid)
        if r.get("done"):
            return r
        time.sleep(0.05)
    raise AssertionError(
        f"{rid} never terminal: {client.serve_status()}")


# ----------------------------------------------------------------------


def test_loadgen_smoke_two_ranks(pool):
    """The CI smoke: a short closed-loop run against a 2-decode-rank
    paged plane — everything offered terminalizes, nothing hangs, the
    report passes the pinned-schema + conservation check, and every
    completed stream is bit-identical to its solo reference."""
    t = attach(pool, "smoke")
    try:
        t.serve_start(SPEC, max_batch=2, max_len=48, pad_to=4,
                      steps=2, queue_depth=8, inflight=64,
                      decode_ranks=2, kv_block_tokens=8, timeout=600)
        cfg = LoadConfig(rps=3.0, duration_s=4.0, seed=1,
                         prompt_len=(2, 5), max_new=(4, 4),
                         drain_s=120.0, detail=True)
        rep = run_load(ClientTransport(t), cfg)
        validate_report(rep)
        assert rep["hung"] == 0 and rep["failed"] == 0, rep
        assert rep["completed"] > 0
        assert rep["slo"]["pass"] is True   # no targets, nothing hung
        assert_completed_bit_identical(t, cfg, rep)
        st = t.serve_status()
        assert len(st["decode_ranks"]) == 2, st
        assert st["kv"]["block_tokens"] == 8
        assert t.serve_stop()["status"] == "stopped"
    finally:
        try:
            t.serve_stop()
        except Exception:
            pass
        t.close(detach=True)


def test_overload_sigkill_drops_exactly_once_multirank(pool):
    """The headline drill: calibrate the plane's sustainable rate,
    then offer ~2x that while a decode rank is SIGKILLed mid-run and
    the survivors drop 8% of frames.  Every accepted request
    terminalizes exactly once — completed streams bit-identical to
    solo, overload handled with EXPLICIT shed/rejected verdicts,
    zero hung — and both decode ranks demonstrably served."""
    t = attach(pool, "chaos")
    ranks_seen: set = set()
    try:
        t.serve_start(SPEC, max_batch=2, max_len=48, pad_to=4,
                      steps=2, queue_depth=4, inflight=64,
                      decode_ranks=2, kv_block_tokens=8, timeout=600)

        # Phase A — calibration at a modest rate (no faults).
        cal = LoadConfig(rps=3.0, duration_s=3.0, seed=11,
                         prompt_len=(2, 5), max_new=(4, 4),
                         drain_s=120.0, detail=True)
        rep_a = run_load(ClientTransport(t), cal)
        validate_report(rep_a)
        assert rep_a["hung"] == 0, rep_a
        rate = rep_a["completed"] / max(rep_a["duration_s"], 1e-9)

        # Phase B — ~2x overload with a mid-run SIGKILL + 8% drops.
        state = {"killed": None, "dropped": False, "n": 0}

        def on_progress(counts, n_open):
            state["n"] += 1
            now = time.monotonic()
            if state["killed"] is None and counts["accepted"] >= 4:
                # Seeded SIGKILL on the HIGHEST decode rank: dies on
                # its 3rd control message — a serve_step mid-decode.
                kill = WORLD - 1
                pool.comm.send_to_ranks([kill], "chaos", {
                    "action": "set",
                    "spec": {"seed": 5, "kill_rank": kill,
                             "kill_at": 3}}, timeout=60)
                state["killed"] = now
            elif state["killed"] is not None \
                    and not state["dropped"] \
                    and now - state["killed"] > 2.0:
                live = sorted(set(range(WORLD))
                              - pool.comm.dead_ranks())
                pool.comm.send_to_ranks(live, "chaos", {
                    "action": "set",
                    "spec": {"seed": 9, "drop": 0.08}}, timeout=60)
                pool.comm.set_fault_plan(FaultPlan(seed=11,
                                                   drop=0.08))
                state["dropped"] = True
            if state["n"] % 25 == 0:
                try:
                    for rk, v in (t.serve_status().get("ranks")
                                  or {}).items():
                        if v.get("placed", 0) > 0:
                            ranks_seen.add(rk)
                except Exception:
                    pass

        over = LoadConfig(rps=max(6.0, 2.0 * rate), duration_s=6.0,
                          seed=12, prompt_len=(2, 5), max_new=(4, 4),
                          drain_s=150.0, detail=True)
        try:
            rep_b = run_load(ClientTransport(t), over,
                             on_progress=on_progress)
        finally:
            pool.comm.set_fault_plan(None)
            live = sorted(set(range(WORLD))
                          - pool.comm.dead_ranks())
            pool.comm.send_to_ranks(live, "chaos",
                                    {"action": "clear"}, timeout=60)

        # Zero silent drops: conservation + zero hung is the contract.
        validate_report(rep_b)
        assert rep_b["hung"] == 0, rep_b
        assert rep_b["failed"] == 0, rep_b
        assert rep_b["completed"] > 0, rep_b
        # 2x overload against a 4-slot plane with a depth-4 queue must
        # shed — with a DELIVERED verdict, never silence.
        assert rep_b["shed"] + rep_b["rejected"] >= 1, rep_b
        # Exactly-once, bit-identical: every completed stream (both
        # phases — phase A's plan is disjoint by seed) equals solo.
        assert_completed_bit_identical(t, cal, rep_a)
        assert_completed_bit_identical(t, over, rep_b)

        st = t.serve_status()
        assert st["failovers"] >= 1, st      # the kill landed
        assert st["replayed"] >= 1, st       # journal re-admission
        assert st["dup_dropped"] == 0, st    # offset dedup never fired
        assert len(st["decode_ranks"]) == 2, st
        # Multi-rank decode demonstrably used >1 rank.
        assert len(ranks_seen) >= 2, \
            f"placements only ever seen on ranks {ranks_seen}"
        # Per-rank KV telemetry reached the heartbeat surface.
        deadline = time.time() + 30
        seen_kvb = False
        while time.time() < deadline and not seen_kvb:
            seen_kvb = any((v.get("srv") or {}).get("kvb")
                           for v in pool.status()["ranks"].values())
            if not seen_kvb:
                time.sleep(1.0)
        assert seen_kvb, "no kvb heartbeat piggyback ever arrived"
        status = pool.status()
        assert not status.get("hang_verdicts"), \
            status["hang_verdicts"]
    finally:
        try:
            t.serve_stop()
        except Exception:
            pass
        t.close(detach=True)


def test_chunked_prefill_keeps_short_stream_alive(pool):
    """A 56-token prompt admitted while a short request decodes: with
    ``prefill_chunk`` armed the prompt streams in 4-token chunks
    BETWEEN decode ticks, so the short stream keeps emitting during
    the prefill window (bounded TPOT) — and both streams stay
    bit-identical to their solo references."""
    t = attach(pool, "chunk")
    try:
        t.serve_start(SPEC, max_batch=2, max_len=64, pad_to=4,
                      steps=1, queue_depth=8, inflight=8,
                      decode_ranks=1, kv_block_tokens=8,
                      prefill_chunk=4, timeout=600)
        short_p, short_n = [5, 9, 2], 30
        long_p, long_n = [((7 * i) % 50) + 1 for i in range(56)], 4
        rid_s = t.serve_submit(short_p, short_n)["rid"]
        # Let the short stream start, then admit the long prompt.
        deadline = time.time() + 60
        while not t.serve_result(rid_s).get("tokens"):
            assert time.time() < deadline
            time.sleep(0.05)
        before = len(t.serve_result(rid_s)["tokens"])
        rid_l = t.serve_submit(long_p, long_n)["rid"]
        # While the long prompt prefills (14 chunks, one per tick),
        # the short stream must keep emitting.
        progressed = 0
        while time.time() < deadline:
            rl = t.serve_result(rid_l)
            n_short = len(t.serve_result(rid_s)["tokens"])
            if not rl.get("tokens") and n_short > before:
                progressed = n_short - before
            if rl.get("tokens") or rl.get("done"):
                break
            time.sleep(0.02)
        assert progressed > 0, \
            "short stream starved during the long prefill"
        rs, rl = wait_result(t, rid_s), wait_result(t, rid_l)
        assert rs["status"] == "completed"
        assert rl["status"] == "completed"
        refs = solo_refs(t, [(short_p, short_n), (long_p, long_n)])
        assert rs["tokens"] == refs[0]
        assert rl["tokens"] == refs[1]
        st = t.serve_status()
        assert st["dup_dropped"] == 0, st
    finally:
        try:
            t.serve_stop()
        except Exception:
            pass
        t.close(detach=True)


def test_stage_attribution_and_metrics_consistency(pool):
    """ISSUE 18 pins on a real loadgen run:

    1. Every completed request's contiguous stage decomposition sums
       to its observed e2e within 10% (the acceptance bound; the
       telescoping construction makes it exact, so we also pin 1ms).
    2. TTFT == admit + queue + kv_alloc + prefill (same tolerance).
    3. The loadgen report and the /metrics exposition agree: the
       accepted/shed/rejected verdict counters and the stage-histogram
       completion count match what the CLIENT observed (satellite 3).
    """
    from nbdistributed_tpu.observability import metrics as obs_metrics
    from nbdistributed_tpu.observability.servingobs import SERVE_STAGES

    def metric(line_prefix):
        text = obs_metrics.registry().prometheus_text()
        for ln in text.splitlines():
            if ln.startswith(line_prefix):
                return float(ln.rsplit(" ", 1)[1])
        return None

    # The registry is process-global and the pool fixture is module-
    # scoped, so earlier tests' serving counters are still in it:
    # every counter assertion below is on the DELTA across this run.
    # The verdict/token counters carry the serving plane's OWN tenant
    # label ("serve" — the manager's name, not the attaching tenant);
    # only the per-request stage histograms attribute to "latpin".
    def counters():
        return {
            "accepted": metric('nbd_serve_requests_total'
                               '{tenant="serve",verdict="accepted"}')
            or 0.0,
            "shed": metric('nbd_serve_requests_total'
                           '{tenant="serve",verdict="shed"}') or 0.0,
            "rejected": metric('nbd_serve_requests_total'
                               '{tenant="serve",verdict="rejected"}')
            or 0.0,
            "tokens": metric('nbd_serve_tokens_total'
                             '{tenant="serve"}') or 0.0,
        }

    t = attach(pool, "latpin")
    try:
        t.serve_start(SPEC, max_batch=2, max_len=48, pad_to=4,
                      steps=2, queue_depth=8, inflight=64,
                      decode_ranks=2, kv_block_tokens=8, timeout=600)
        before = counters()
        cfg = LoadConfig(rps=3.0, duration_s=4.0, seed=7,
                         prompt_len=(2, 5), max_new=(4, 4),
                         drain_s=120.0)
        rep = run_load(ClientTransport(t), cfg)
        validate_report(rep)
        assert rep["completed"] > 0 and rep["hung"] == 0, rep

        st = t.serve_status()
        lat = st.get("lat") or {}
        recs = lat.get("records") or []
        finished = [r for r in recs
                    if r["status"] in ("completed", "failed")]
        assert len(finished) >= rep["completed"], (len(finished), rep)
        for r in finished:
            total = sum(r["stages"][s] for s in SERVE_STAGES)
            assert abs(total - r["e2e_s"]) <= max(1e-3,
                                                  0.10 * r["e2e_s"]), \
                (r["rid"], total, r["e2e_s"], r["stages"])
            ttft = (r["stages"]["admit"] + r["stages"]["queue"]
                    + r["stages"]["kv_alloc"] + r["stages"]["prefill"])
            assert abs(ttft - r["ttft_s"]) <= 1e-3, (r["rid"], r)
            assert all(r["stages"][s] >= 0.0 for s in SERVE_STAGES), r
        summ = lat.get("summary") or {}
        assert summ.get("count", 0) >= rep["completed"]

        # Report <-> /metrics consistency: the exposition text is
        # exactly what the scrape endpoint serves.
        after = counters()
        assert after["accepted"] - before["accepted"] \
            == rep["accepted"], (before, after, rep)
        assert after["shed"] - before["shed"] == rep["shed"], \
            (before, after, rep)
        assert after["rejected"] - before["rejected"] \
            == rep["rejected"], (before, after, rep)
        assert after["tokens"] - before["tokens"] \
            >= rep["tokens_total"], (before, after, rep)
        # One stage-histogram observation per finished request, and
        # the stage attribution carries the ATTACHING tenant's name
        # ("latpin" is unique to this test, so no delta needed).
        n = metric('nbd_serve_stage_seconds_count'
                   '{stage="decode",tenant="latpin"}')
        assert n == len(finished), (n, len(finished))
    finally:
        try:
            t.serve_stop()
        except Exception:
            pass
        t.close(detach=True)
