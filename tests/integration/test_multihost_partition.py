"""Acceptance tests for ISSUE 6: real multi-host execution with a
partition-tolerant control plane.

The two-"host" world here is the **multi-address fallback** from the
issue: the far host is a real ``nbd_agent`` daemon bound to a distinct
non-loopback-semantics address (``127.0.1.x``), with its OWN run dir
and no shared session manifest — frames genuinely cross the
authenticated (``NBDA``) link, worker spawn/death-watch/stdio go
through the agent protocol, and the shared-filesystem assumption is
actually off (the far side's reconnect endpoint comes from the
hello-mirrored manifest, not a file).  The network-namespace + veth
variant lives in ``test_netns_real_link`` and skips (loudly) where the
kernel can't move a veth peer across namespaces.

Scenarios:

1. ``test_partition_orphan_reattach_exactly_once`` — a 4-rank world
   split across two hosts runs a real collective cell over the link; a
   seeded ``FaultPlan`` link partition opens mid-cell; the far side
   orphans and is NOT healed during the partition grace; the link
   heals; the fleet reattaches and the in-flight result is delivered
   exactly once.  Then a uniformly-slow link (latency, no partition)
   produces zero supervisor heals and zero watchdog verdicts.
2. ``test_stale_epoch_fenced_after_partition`` — the split-brain arm:
   the coordinator adopts a newer epoch while the far side is
   partitioned away; the stale side's results are rejected on
   reconnect (never double-applied) until a hello hands it the new
   tenancy.
"""

import os
import shutil
import subprocess
import sys
import threading
import time

import pytest

from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
from nbdistributed_tpu.manager.multihost import HostSpec
from nbdistributed_tpu.messaging import CommunicationManager
from nbdistributed_tpu.observability import flightrec
from nbdistributed_tpu.observability import metrics as obs_metrics
from nbdistributed_tpu.resilience import session
from nbdistributed_tpu.resilience.faults import FaultPlan
from nbdistributed_tpu.resilience.supervisor import (Supervisor,
                                                     SupervisorPolicy)
from nbdistributed_tpu.resilience.watchdog import HangPolicy, HangWatchdog

pytestmark = [pytest.mark.integration, pytest.mark.faults,
              pytest.mark.multihost]

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

TOKEN = "mh-it-secret"
COORD_ADDR = "127.0.1.10"     # non-loopback-semantics dial address
AGENT_ADDR = "127.0.1.12"


def _addr_bindable(addr: str) -> bool:
    import socket
    try:
        s = socket.socket()
        s.bind((addr, 0))
        s.close()
        return True
    except OSError:
        return False


def _agent_env() -> dict:
    """Scrubbed env for the agent daemon (and so for the workers it
    spawns): no TPU platform grab, CPU backend defaults."""
    from nbdistributed_tpu.manager import topology
    env = topology.cpu_worker_env()
    env.pop("NBD_RUN_DIR", None)   # the agent minds its OWN run dir
    env.pop("NBD_FAULT_PLAN", None)
    return env


def _start_agent(tmp_path, label: str, addr: str):
    """Spawn tools/nbd_agent.py, wait for its READY line, return
    (proc, port, run_dir)."""
    run_dir = str(tmp_path / f"run_{label}")
    os.makedirs(run_dir, exist_ok=True)
    secret = tmp_path / f"{label}.secret"
    secret.write_text(TOKEN)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "nbd_agent.py"),
         "--bind", addr, "--port", "0", "--token-file", str(secret),
         "--host-label", label, "--run-dir", run_dir],
        cwd=REPO_ROOT, env=_agent_env(),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.time() + 60
    port = None
    while time.time() < deadline:
        line = proc.stdout.readline().decode("utf-8", "replace")
        if not line:
            raise AssertionError(
                f"agent {label} died during bring-up (rc "
                f"{proc.poll()})")
        if line.startswith("NBD_AGENT_READY"):
            port = int(dict(kv.split("=", 1)
                            for kv in line.split()[1:])["port"])
            break
    assert port is not None, f"agent {label} never printed READY"
    return proc, port, run_dir


def _bring_up(tmp_path, monkeypatch, world_local: int, world_far: int,
              request_timeout=None):
    """Two-host world: ``world_local`` direct children + ``world_far``
    agent-spawned on hostB at a distinct 127.0.1.x address.  Returns
    (comm, pm, agent_proc, far_run_dir, mirror)."""
    run_a = str(tmp_path / "run_local")
    os.makedirs(run_a, exist_ok=True)
    monkeypatch.setenv("NBD_RUN_DIR", run_a)
    flightrec.reset_for_tests()
    agent_proc, agent_port, run_b = _start_agent(tmp_path, "hostB",
                                                 AGENT_ADDR)
    world = world_local + world_far
    comm = CommunicationManager(num_workers=world, host="0.0.0.0",
                                auth_token=TOKEN,
                                timeout=request_timeout,
                                session_token="sess-tok",
                                session_epoch=1)
    pm = ProcessManager()
    pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
    try:
        pm.start_workers_multihost(
            [HostSpec("local", world_local),
             HostSpec("hostB", world_far)],
            comm.port, coordinator_host=COORD_ADDR, backend="cpu",
            auth_token=TOKEN,
            agents={"hostB": (AGENT_ADDR, agent_port)},
            extra_env={"NBD_SESSION_TOKEN": "sess-tok",
                       "NBD_SESSION_EPOCH": "1",
                       "NBD_ORPHAN_TTL_S": "120"})
        assert pm.hosts == {**{r: "local" for r in range(world_local)},
                            **{r: "hostB" for r in
                               range(world_local, world)}}
        comm.set_host_map(pm.hosts)
        wait_until_ready(comm, pm, 240)
        # Manifest mirror via hello: the far host shares no run dir,
        # so this is its ONLY endpoint-discovery channel.
        mirror = session.make_manifest(
            world_size=world, control_host=COORD_ADDR,
            control_port=comm.port, bind_host="0.0.0.0",
            token="sess-tok", epoch=1,
            pids={r: p.pid for r, p in pm.processes.items()},
            backend="cpu", dist_port=pm.dist_port)
        hello = comm.send_to_all(
            "hello", {"token": "sess-tok", "epoch": 1,
                      "manifest": mirror}, timeout=30)
        assert all(
            (m.data or {}).get("status") == "ok"
            for m in hello.values()), hello
    except Exception:
        pm.shutdown()
        comm.shutdown()
        agent_proc.terminate()
        raise
    return comm, pm, agent_proc, run_b, mirror


def _teardown(comm, pm, agent_proc):
    try:
        pm.shutdown()
    finally:
        comm.shutdown()
        agent_proc.terminate()
        try:
            agent_proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            agent_proc.kill()


def _counter(name: str) -> float:
    return (obs_metrics.registry().to_json()["counters"].get(name)
            or 0.0)


@pytest.mark.skipif(not _addr_bindable(AGENT_ADDR),
                    reason="cannot bind 127.0.1.x on this host")
def test_partition_orphan_reattach_exactly_once(tmp_path, monkeypatch):
    comm, pm, agent_proc, run_b, _mirror = _bring_up(
        tmp_path, monkeypatch, world_local=2, world_far=2)
    sup = None
    wd = None
    try:
        world = 4
        far = [2, 3]
        streamed = []
        comm.set_output_callback(
            lambda r, d: streamed.append((r, d.get("text", ""))))

        # --- phase 1: a real collective cell over the link ----------
        resp = comm.send_to_all(
            "execute",
            "print(f'over-the-link-{rank}')\n"
            "total = float(all_reduce(jnp.array([rank + 1.0]))[0])\n"
            "total", timeout=240)
        for r in range(world):
            assert not resp[r].data.get("error"), resp[r].data
            assert resp[r].data["output"].strip().endswith("10.0")
        assert any(r in far and "over-the-link" in t
                   for r, t in streamed), \
            "no stdout streamed back across the agent-host link"

        # --- phase 2: seeded partition mid-cell ---------------------
        sup_heals = []
        sup = Supervisor(SupervisorPolicy(
            poll_s=0.3, degraded_after_s=3.0, postmortem=False,
            partition_grace_s=90.0),
            heal=lambda: sup_heals.append(time.time()) or None)
        sup.attach(comm, pm)

        link_spec = {"links": [{"hosts": ["local", "hostB"],
                                "after_s": 2.0, "for_s": 12.0}]}
        acks = comm.send_to_all("chaos", {"action": "set",
                                          "spec": link_spec},
                                timeout=30)
        assert all(m.data.get("status") == "armed"
                   for m in acks.values()), acks
        comm.set_fault_plan(FaultPlan.from_spec(link_spec))

        cell_err = []

        def _dispatch():
            try:
                comm.send_to_all(
                    "execute",
                    "import time as _t\n_t.sleep(6.0)\n"
                    "inflight = rank * 100 + 7\ninflight",
                    timeout=60)
            except Exception as e:
                cell_err.append(e)

        t = threading.Thread(target=_dispatch, daemon=True)
        t.start()
        t.join(timeout=90)
        assert not t.is_alive(), "partitioned cell dispatch wedged"
        # The far side severed mid-cell: the pending request aborts.
        assert cell_err, "partition never aborted the in-flight request"

        # Suspected partition, NOT N deaths: the supervisor flags the
        # host and defers healing for the grace window.
        deadline = time.time() + 30
        while time.time() < deadline:
            if "hostB" in sup.status()["suspected_hosts"]:
                break
            time.sleep(0.2)
        assert "hostB" in sup.status()["suspected_hosts"], \
            sup.status()
        assert not sup_heals, "healed during partition grace!"
        assert _counter(
            'nbd_partition_suspected_total{source="supervisor"}') >= 1

        # --- phase 3: the link heals; the fleet reattaches ----------
        deadline = time.time() + 60
        while time.time() < deadline:
            if sorted(comm.connected_ranks()) == list(range(world)):
                break
            time.sleep(0.3)
        assert sorted(comm.connected_ranks()) == list(range(world)), (
            comm.connected_ranks(), pm.startup_diagnostics())
        assert _counter("nbd_link_reconnects_total") >= len(far)
        # Suspicion clears; still zero heals.
        deadline = time.time() + 20
        while time.time() < deadline and sup.status()["suspected_hosts"]:
            time.sleep(0.2)
        assert sup.status()["suspected_hosts"] == {}
        assert not sup_heals

        # The in-flight result was parked far-side and is delivered
        # EXACTLY once.
        drained = session.drain_mailboxes(comm, timeout=30)
        far_results = {r: v for r, v in drained.items() if v}
        assert sorted(far_results) == far, drained
        for r in far:
            vals = list(far_results[r].values())
            assert len(vals) == 1
            assert vals[0].get("output", "").strip() \
                == str(r * 100 + 7), vals
        again = session.drain_mailboxes(comm, timeout=30)
        assert all(not v for v in again.values()), (
            "second drain redelivered a claimed result", again)

        # Zero double-execution anywhere: every rank ran the cell
        # exactly once (namespace value present and correct).
        got = comm.send_to_all("get_var", {"name": "inflight"},
                               timeout=30)
        for r in range(world):
            assert got[r].data.get("value") == r * 100 + 7

        # Far-side black boxes (per-host run dir!) recorded the
        # episode: transport EOF → orphan → reattach.
        for r in far:
            ring = flightrec.read_latest(run_b, f"rank{r}")
            assert ring is not None, f"no far-side ring for rank {r}"
            kinds = [e.get("t") for e in ring["events"]]
            assert "transport_eof" in kinds, kinds[-20:]
            assert "orphan_entered" in kinds, kinds[-20:]
            assert "orphan_reattached" in kinds, kinds[-20:]

        # The mesh survived: a fresh collective still works.
        resp = comm.send_to_all(
            "execute",
            "again = float(all_reduce(jnp.array([1.0]))[0])\nagain",
            timeout=240)
        for r in range(world):
            assert resp[r].data["output"].strip() == str(world * 1.0)

        # --- phase 4: uniformly-slow link ⇒ zero verdicts/heals -----
        comm.set_fault_plan(None)
        comm.send_to_all("chaos", {"action": "clear"}, timeout=30)
        slow_spec = {"links": [{"hosts": ["local", "hostB"],
                                "latency_s": 0.25}]}
        comm.send_to_all("chaos", {"action": "set", "spec": slow_spec},
                         timeout=30)
        comm.set_fault_plan(FaultPlan.from_spec(slow_spec))
        # skew_s must exceed ping cadence (2 s) + link latency, or
        # heartbeat propagation lag alone fakes divergence (the PR 5
        # false-positive analysis); 6 s is still far below the cell.
        wd = HangWatchdog(HangPolicy(poll_s=0.3, skew_s=6.0,
                                     stall_s=8.0, escalate=()))
        wd.attach(comm, pm)
        resp = comm.send_to_all(
            "execute",
            "import time as _t\n"
            "for _i in range(3):\n"
            "    _t.sleep(0.8)\n"
            "    s = float(all_reduce(jnp.array([1.0]))[0])\n"
            "s", timeout=240)
        for r in range(world):
            assert resp[r].data["output"].strip() == str(world * 1.0)
        time.sleep(1.0)  # a few more watchdog polls on the idle world
        assert wd.verdicts_total == 0, wd.status()
        assert not sup_heals
        assert sup.status()["suspected_hosts"] == {}
        comm.send_to_all("chaos", {"action": "clear"}, timeout=30)
    finally:
        if wd is not None:
            wd.stop()
        if sup is not None:
            sup.stop()
        _teardown(comm, pm, agent_proc)


# ----------------------------------------------------------------------
# network-namespace + veth variant: a REAL link, a REAL link-down


_NETNS_PROBE = """
set -e
ip link set lo up
unshare -n sleep 5 &
pid=$!
sleep 0.3
ip link add pvA type veth peer name pvB
ip link set pvB netns $pid
"""


def _netns_support() -> tuple:
    """Can this kernel give us two unprivileged network namespaces
    joined by a veth pair?  Probes the EXACT operations the scenario
    needs, so the skip reason names what's missing."""
    for tool in ("unshare", "ip"):
        if shutil.which(tool) is None:
            return False, f"'{tool}' is not installed"
    try:
        r = subprocess.run(["unshare", "-Urn", "sh", "-c",
                            _NETNS_PROBE],
                           capture_output=True, timeout=30)
    except (subprocess.TimeoutExpired, OSError) as e:
        return False, f"unshare probe failed to run: {e}"
    if r.returncode != 0:
        err = (r.stderr or r.stdout or b"").decode(
            "utf-8", "replace").strip().splitlines()
        return False, ("kernel refused unprivileged netns+veth setup"
                       + (f" ({err[-1]})" if err else ""))
    return True, ""


def test_netns_real_link(tmp_path):
    """Frames cross an actual veth device between two network
    namespaces; the partition is a real ``ip link set ... down``.
    Skips — loudly, with the reason — where the kernel can't do
    unprivileged netns+veth (e.g. 4.4-era kernels)."""
    ok, reason = _netns_support()
    if not ok:
        pytest.skip(f"two-namespace veth world unavailable: {reason}")
    env = _agent_env()
    r = subprocess.run(
        ["unshare", "-Urn", sys.executable,
         os.path.join(REPO_ROOT, "tests", "integration",
                      "_netns_world.py"), str(tmp_path)],
        env=env, cwd=REPO_ROOT, capture_output=True, timeout=420)
    result_path = tmp_path / "result.json"
    result = {}
    if result_path.exists():
        import json
        result = json.loads(result_path.read_text())
    assert r.returncode == 0 and result.get("ok"), (
        "netns world failed:\n"
        + (r.stdout or b"").decode("utf-8", "replace")[-4000:]
        + (r.stderr or b"").decode("utf-8", "replace")[-2000:]
        + f"\nresult: {result}")
    assert result.get("streamed_far"), result
    assert result.get("suspected"), result
    assert result.get("heals") == 0, result


@pytest.mark.skipif(not _addr_bindable(AGENT_ADDR),
                    reason="cannot bind 127.0.1.x on this host")
def test_stale_epoch_fenced_after_partition(tmp_path, monkeypatch):
    """Split-brain resolution: the coordinator adopts a newer epoch
    while the far side is partitioned away (the 'healed replacements
    meanwhile' tenancy change); when the link heals, the stale side's
    results are rejected — never double-applied — until a hello hands
    it the new epoch."""
    comm, pm, agent_proc, run_b, _mirror = _bring_up(
        tmp_path, monkeypatch, world_local=1, world_far=1)
    try:
        link_spec = {"links": [{"hosts": ["local", "hostB"],
                                "after_s": 1.0, "for_s": 10.0}]}
        acks = comm.send_to_all("chaos", {"action": "set",
                                          "spec": link_spec},
                                timeout=30)
        assert all(m.data.get("status") == "armed"
                   for m in acks.values())
        comm.set_fault_plan(FaultPlan.from_spec(link_spec))

        cell_err = []

        def _dispatch():
            try:
                comm.send_to_all(
                    "execute",
                    "import time as _t\n_t.sleep(4.0)\n"
                    "split = rank + 500\nsplit", timeout=60)
            except Exception as e:
                cell_err.append(e)

        t = threading.Thread(target=_dispatch, daemon=True)
        t.start()
        t.join(timeout=90)
        assert cell_err, "partition never aborted the request"

        # Tenancy change while the far side is unreachable (what a
        # %dist_attach / heal-with-replacements does to the epoch).
        comm.session_epoch = 2
        comm.set_fault_plan(None)  # coordinator side: link is "up" for
        # the new tenancy; the far worker's own plan still blocks it
        # until the window closes.
        hello0 = comm.send_to_rank(
            0, "hello", {"token": "sess-tok", "epoch": 2}, timeout=30)
        assert hello0.data.get("status") == "ok"

        # The stale side reconnects once ITS window closes.
        deadline = time.time() + 60
        while time.time() < deadline:
            if sorted(comm.connected_ranks()) == [0, 1]:
                break
            time.sleep(0.3)
        assert sorted(comm.connected_ranks()) == [0, 1]

        # Its replies are stamped with the superseded epoch 1 and the
        # coordinator refuses to apply them: the request times out
        # rather than double-applying a stale result.
        rejected_before = _counter("nbd_epoch_rejected_results")
        with pytest.raises(TimeoutError):
            comm.send_to_rank(1, "get_status", timeout=6)
        assert _counter("nbd_epoch_rejected_results") > rejected_before

        # A hello hands rank 1 the new tenancy; it serves again, and
        # the parked in-flight result is claimable exactly once.
        hello1 = comm.send_to_rank(
            1, "hello", {"token": "sess-tok", "epoch": 2}, timeout=30)
        assert hello1.data.get("status") == "ok"
        assert hello1.data.get("parked"), "in-flight result not parked"
        st = comm.send_to_rank(1, "get_status", timeout=30)
        assert st.data.get("session_epoch") == 2
        drained = session.drain_mailboxes(comm, timeout=30)
        vals = list((drained.get(1) or {}).values())
        assert len(vals) == 1 \
            and vals[0].get("output", "").strip() == "501", drained
        again = session.drain_mailboxes(comm, timeout=30)
        assert not again.get(1), again
        # Exactly-once: the cell ran once on the stale side, never
        # re-executed through all of this.
        got = comm.send_to_rank(1, "get_var", {"name": "split"},
                                timeout=30)
        assert got.data.get("value") == 501
    finally:
        _teardown(comm, pm, agent_proc)
