"""Acceptance test for unified observability (ISSUE 2).

A 4-rank CPU/gloo world runs a traced cell sequence **under an active
FaultPlan** (frame drops + duplicates on both control-plane directions,
with redelivery enabled).  The session must produce:

1. one merged Chrome-trace JSON containing coordinator spans AND
   handler spans from every rank, stitched under a single trace id, on
   an aligned timebase (each worker's handle/execute span, after clock
   correction, lies inside the coordinator send span that caused it),
   with the fault plan's decisions folded in as instant events;
2. metrics-registry numbers consistent with the chaos run's
   ``get_status`` counters (dedup hits, fault injections) and with the
   coordinator's ``retries_sent``.
"""

import json

import pytest

from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
from nbdistributed_tpu.messaging import CommunicationManager
from nbdistributed_tpu.observability import metrics as obs_metrics
from nbdistributed_tpu.observability.export import (merge_trace,
                                                    save_trace)
from nbdistributed_tpu.resilience import FaultPlan, RetryPolicy

pytestmark = [pytest.mark.integration, pytest.mark.faults,
              pytest.mark.obs]

WORLD = 4
ATTACH_TIMEOUT = 180
TRACE_ID = "obs0acceptance00"

# Aggressive redelivery so the run makes progress through frame loss
# without waiting out whole request deadlines.
RETRY = RetryPolicy(attempts=6, attempt_timeout_s=2.0,
                    backoff_base_s=0.1, backoff_max_s=0.5, jitter=0.25)


def outputs(responses):
    return {r: m.data.get("output") for r, m in responses.items()}


def _gauge(snap: dict, name: str) -> float:
    return sum(v for k, v in snap.get("gauges", {}).items()
               if k == name or k.startswith(name + "{"))


def test_traced_chaos_run_merges_and_matches_counters(tmp_path):
    env = {"NBD_FAULT_PLAN": json.dumps(
        {"seed": 77, "drop": 0.08, "duplicate": 0.05})}
    comm = CommunicationManager(num_workers=WORLD, timeout=60,
                                retry=RETRY)
    pm = ProcessManager()
    pm.add_death_callback(lambda rank, rc: comm.mark_worker_dead(rank))
    try:
        pm.start_workers(WORLD, comm.port, backend="cpu",
                         extra_env=env)
        wait_until_ready(comm, pm, ATTACH_TIMEOUT)
    except Exception:
        pm.shutdown()
        comm.shutdown()
        raise
    plan = FaultPlan(seed=78, drop=0.08, duplicate=0.05)
    comm.set_fault_plan(plan)
    try:
        # --- traced chaos phase --------------------------------------
        comm.send_to_all("trace", {"action": "start",
                                   "trace_id": TRACE_ID}, timeout=60)
        comm.tracer.start(trace_id=TRACE_ID)
        comm.send_to_all("execute", "counter = 0", timeout=60)
        n = 8
        for _ in range(n):
            comm.send_to_all("execute", "counter += 1", timeout=60)
        out = outputs(comm.send_to_all("execute", "counter", timeout=60))
        assert out == {r: str(n) for r in range(WORLD)}, \
            f"double- or missed executions under chaos: {out}"
        comm.tracer.stop()

        # --- counter consistency: get_status vs metrics registry -----
        # dedup_hits is monotonic and the probes are separate requests,
        # so bracket the registry snapshot between two status probes.
        st1 = comm.send_to_all("get_status", timeout=60)
        mets = comm.send_to_all("metrics", {}, timeout=60)
        st2 = comm.send_to_all("get_status", timeout=60)
        total_dedup = 0
        for r in range(WORLD):
            # the status probe also reports observability state now
            assert st1[r].data.get("tracing") is True
            snap = mets[r].data["metrics"]
            lo = st1[r].data.get("dedup_hits", 0)
            hi = st2[r].data.get("dedup_hits", 0)
            got = _gauge(snap, "nbd_dedup_hits")
            assert lo <= got <= hi, \
                f"rank {r}: registry dedup {got} outside [{lo}, {hi}]"
            total_dedup += got
            # fault injections mirrored from the plan counters
            inj_lo = sum((st1[r].data.get("fault_counters") or {}).get(k, 0)
                         for k in ("dropped", "duplicated"))
            inj = sum(v for k, v in snap.get("gauges", {}).items()
                      if k.startswith("nbd_fault_injections")
                      and ('action="dropped"' in k
                           or 'action="duplicated"' in k))
            assert inj >= inj_lo >= 1, \
                f"rank {r}: fault injections not mirrored ({inj})"
            # wire accounting ran on the worker
            assert any(k.startswith("nbd_wire_messages_total")
                       for k in snap["counters"])
        # the fixed seeds guarantee loss, so redelivery must have fired
        # and must agree with the registry's counter
        assert comm.retries_sent >= 1
        # The registry is process-global (other tests' managers may
        # have counted too), so it bounds from above.
        reg_retries = sum(
            v for k, v in
            obs_metrics.registry().to_json()["counters"].items()
            if k.startswith("nbd_retries_total"))
        assert reg_retries >= comm.retries_sent
        assert total_dedup >= 1, "chaos run exercised no redelivery"

        # --- merged export -------------------------------------------
        dumps = comm.send_to_all("trace", {"action": "dump"},
                                 timeout=60)
        comm.send_to_all("trace", {"action": "stop"}, timeout=60)
        merged = merge_trace(
            comm.tracer.dump(),
            {r: m.data["trace"] for r, m in dumps.items()},
            comm.clock.offsets(),
            coordinator_faults=plan.events(),
            rank_faults={r: m.data.get("fault_events") or []
                         for r, m in dumps.items()})
        path = str(tmp_path / "merged_trace.json")
        save_trace(path, merged)
        with open(path) as f:
            loaded = json.load(f)

        evs = loaded["traceEvents"]
        for e in evs:
            assert {"name", "ph", "pid"} <= set(e)
            if e["ph"] != "M":
                assert "ts" in e
        spans = [e for e in evs if e["ph"] == "X"]
        pids = {e["pid"] for e in spans}
        assert pids >= {-1, 0, 1, 2, 3}, \
            f"merged trace missing processes: {sorted(pids)}"
        # one trace id stitches the session together
        tids = {e["args"].get("trace_id") for e in spans}
        assert tids == {TRACE_ID}, tids
        # fault instant events made it into the merge
        faults = [e for e in evs
                  if e["ph"] == "i" and e["cat"] == "fault"]
        assert faults, "no fault instant events in the merged trace"
        assert any(e["name"] in ("fault:drop", "fault:duplicate")
                   for e in faults)

        # --- aligned timebase ----------------------------------------
        # Every worker handle/* span whose parent is a coordinator
        # send span must lie INSIDE that span after clock correction
        # (modest slack for estimator error on a shared host).
        coord = {e["args"]["span_id"]: e for e in spans
                 if e["pid"] == -1}
        checked = 0
        slack_us = 0.5e6
        for e in spans:
            if e["pid"] < 0 or not e["name"].startswith("handle/"):
                continue
            parent = coord.get(e["args"].get("parent_id"))
            if parent is None:
                continue
            checked += 1
            assert parent["ts"] - slack_us <= e["ts"], \
                (e["name"], e["pid"])
            assert (e["ts"] + e["dur"]
                    <= parent["ts"] + parent["dur"] + slack_us), \
                (e["name"], e["pid"])
        assert checked >= WORLD * n, \
            f"too few parented worker spans ({checked})"
        # clock estimator actually produced per-rank offsets
        assert set(comm.clock.offsets()) == set(range(WORLD))
    finally:
        try:
            comm.post(list(range(WORLD)), "shutdown")
        except Exception:
            pass
        pm.shutdown()
        comm.shutdown()
