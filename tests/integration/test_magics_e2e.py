"""Notebook-surface integration: drive the magics through a real IPython
shell with real worker subprocesses — the acceptance scenario the
reference only demonstrated in its demo notebook (SURVEY §2.1 #21).
"""

import pytest

pytestmark = [pytest.mark.integration]


@pytest.fixture(scope="module")
def ip():
    from IPython.testing.globalipapp import get_ipython, start_ipython

    # start_ipython() returns the shell only on its FIRST call per
    # process; any earlier IPython-driving module leaves it None.
    shell = start_ipython() or get_ipython()
    shell.run_line_magic("load_ext", "nbdistributed_tpu")
    shell.run_line_magic(
        "dist_init", "-n 2 --backend cpu --attach-timeout 180 -t 120")
    from nbdistributed_tpu.magics.magic import DistributedMagics
    assert DistributedMagics._comm is not None, "cluster failed to start"
    yield shell
    shell.run_line_magic("dist_shutdown", "")


def run(ip, code):
    result = ip.run_cell(code)
    return result


def test_plain_cell_auto_distributes(ip, capsys):
    run(ip, "auto_val = rank * 5 + 1\nauto_val")
    out = capsys.readouterr().out
    assert "Rank 0" in out and "1" in out
    assert "Rank 1" in out and "6" in out


def test_rank_magic_targets_subset(ip, capsys):
    run(ip, "%%rank [1]\n'only-one'")
    out = capsys.readouterr().out
    assert "Rank 1" in out and "only-one" in out
    assert "Rank 0:" not in out


def test_rank_magic_bad_spec_reports(ip, capsys):
    run(ip, "%%rank [9]\n1+1")
    out = capsys.readouterr().out
    assert "out of range" in out


def test_collective_subset_warning(ip, capsys):
    # Reference a collective without calling it: actually running one on
    # a subset would genuinely deadlock the mesh — which is the hazard
    # this warning exists for.
    run(ip, "%%rank [0]\nalias = all_reduce")
    out = capsys.readouterr().out
    assert "deadlock" in out.lower()


def test_sync_magic(ip, capsys):
    ip.run_line_magic("sync", "")
    out = capsys.readouterr().out
    assert "synchronized" in out


def test_status_magic(ip, capsys):
    ip.run_line_magic("dist_status", "")
    out = capsys.readouterr().out
    assert "Rank 0" in out and "Rank 1" in out
    assert "running" in out
    assert "backend=cpu" in out


def test_status_magic_shows_busy_without_stalling(ip, capsys):
    """%dist_status during a long cell must return promptly (busy ranks
    are not probed — their serial loop cannot answer) and report the
    running cell from the heartbeat payload."""
    import threading
    import time as _time

    from nbdistributed_tpu.magics.magic import DistributedMagics

    comm = DistributedMagics._comm
    t = threading.Thread(
        target=lambda: comm.send_to_all(
            "execute", "import time\ntime.sleep(6)\n'slow'",
            timeout=120),
        daemon=True)
    t.start()
    try:
        # EVERY rank must have reported busy before the magic runs —
        # a rank whose busy ping is still in flight would be probed
        # via its (blocked) serial loop and stall the full timeout.
        deadline = _time.time() + 30
        while _time.time() < deadline:
            pings = [comm.last_ping(r) for r in range(2)]
            if all(p and p[1].get("busy_type") == "execute"
                   for p in pings):
                break
            _time.sleep(0.2)
        else:
            raise AssertionError("not all ranks reported busy")
        capsys.readouterr()
        t0 = _time.time()
        ip.run_line_magic("dist_status", "")
        elapsed = _time.time() - t0
        out = capsys.readouterr().out
        assert "busy: execute running" in out, out
        assert elapsed < 4.0, f"status stalled {elapsed:.1f}s on busy ranks"
    finally:
        t.join(timeout=60)


def test_error_reported_per_rank(ip, capsys):
    run(ip, "if rank == 1:\n    raise ValueError('r1 only')")
    out = capsys.readouterr().out
    assert "Rank 1" in out and "r1 only" in out


def test_dist_pull_array(ip, capsys):
    run(ip, "pull_me = jnp.arange(4.0) * (rank + 1)")
    capsys.readouterr()
    ip.run_line_magic("dist_pull", "pull_me --rank 1 --as pulled")
    out = capsys.readouterr().out
    assert "✅" in out
    import numpy as np
    np.testing.assert_allclose(ip.user_ns["pulled"],
                               np.arange(4.0) * 2)


def test_dist_push_array(ip, capsys):
    import numpy as np
    ip.user_ns["pushed"] = np.full((3,), 9.0, np.float32)
    ip.run_line_magic("dist_push", "pushed")
    capsys.readouterr()
    run(ip, "float(pushed.sum())")
    out = capsys.readouterr().out
    assert "27.0" in out


def test_dist_pull_push_params_pytree(ip, capsys):
    """%dist_pull / %dist_push carry a params pytree on the buffer
    path (treedef JSON + leaf bufs, no pickle): structure and arrays
    round-trip kernel <-> workers."""
    import numpy as np
    run(ip, "tree_var = {'w': jnp.arange(6.0).reshape(2, 3),"
            " 'b': {'scale': jnp.ones(3) * (rank + 1), 'step': 4}}")
    capsys.readouterr()
    ip.run_line_magic("dist_pull", "tree_var --rank 1 --as tree_pulled")
    out = capsys.readouterr().out
    assert "pytree" in out and "3 array leaves" not in out  # 2 leaves
    got = ip.user_ns["tree_pulled"]
    np.testing.assert_allclose(got["w"],
                               np.arange(6.0).reshape(2, 3))
    np.testing.assert_allclose(got["b"]["scale"], np.ones(3) * 2)
    assert got["b"]["step"] == 4
    # Round-trip back to every worker under a new name.
    ip.user_ns["tree_back"] = got
    ip.run_line_magic("dist_push", "tree_back")
    capsys.readouterr()
    run(ip, "float(tree_back['b']['scale'].sum())")
    out = capsys.readouterr().out
    assert "6.0" in out      # rank-1's values landed on both ranks


def test_ide_proxies_after_distributed_cell(ip):
    run(ip, "proxy_target = jnp.zeros((5, 6))")
    import jax
    assert isinstance(ip.user_ns.get("proxy_target"), jax.ShapeDtypeStruct)
    assert ip.user_ns["proxy_target"].shape == (5, 6)


def test_dist_mode_toggle_runs_locally(ip, capsys):
    ip.run_line_magic("dist_mode", "-d")
    capsys.readouterr()
    run(ip, "local_only = 'kernel'\nprint('ran locally')")
    out = capsys.readouterr().out
    assert "ran locally" in out
    assert "Rank" not in out
    assert ip.user_ns["local_only"] == "kernel"
    ip.run_line_magic("dist_mode", "-e")
    capsys.readouterr()


def test_magic_cells_not_auto_wrapped(ip, capsys):
    run(ip, "%dist_debug")
    out = capsys.readouterr().out
    assert "world size" in out


def test_timeline_records_distributed_cells(ip, capsys):
    run(ip, "tl_probe = 1")
    capsys.readouterr()
    ip.run_line_magic("timeline_show", "")
    out = capsys.readouterr().out
    assert "tl_probe" in out
    assert "distributed" in out


def test_timeline_save(ip, capsys, tmp_path):
    path = tmp_path / "tl.json"
    ip.run_line_magic("timeline_save", str(path))
    out = capsys.readouterr().out
    assert "saved" in out and path.exists()


def test_namespace_info_magic_surface(ip, capsys):
    ip.run_line_magic("dist_sync_ide", "")
    out = capsys.readouterr().out
    assert "synced" in out


def test_checkpoint_and_restore_magics(ip, capsys, tmp_path):
    path = tmp_path / "magic_ck"
    run(ip, "ckm_v = jnp.arange(4.0) + rank")
    capsys.readouterr()
    ip.run_line_magic("dist_checkpoint", f"{path} ckm_v")
    out = capsys.readouterr().out
    assert "2 ranks saved" in out and "ckm_v (1 leaves)" in out
    run(ip, "ckm_v = 'clobbered'")
    capsys.readouterr()
    ip.run_line_magic("dist_restore", str(path))
    out = capsys.readouterr().out
    assert "2 ranks restored" in out
    run(ip, "float(ckm_v[3])")
    out = capsys.readouterr().out
    assert "3.0" in out and "4.0" in out


def test_checkpoint_missing_var_reports_per_rank(ip, capsys, tmp_path):
    ip.run_line_magic("dist_checkpoint",
                      f"{tmp_path / 'ck_missing'} not_a_var")
    out = capsys.readouterr().out
    assert "❌" in out and "not_a_var" in out


def test_background_checkpoint_and_status(ip, capsys, tmp_path):
    """--background returns immediately; --status polls until done;
    the written checkpoint restores exactly."""
    import time

    path = tmp_path / "magic_ck_bg"
    run(ip, "ckbg_v = jnp.arange(6.0) * (rank + 1)")
    capsys.readouterr()
    ip.run_line_magic("dist_checkpoint", f"{path} ckbg_v --background")
    out = capsys.readouterr().out
    assert "background save started" in out
    # Each rank's "done" is reported exactly once (the status poll
    # consumes the handle), and ranks can finish on different polls —
    # accumulate across polls.
    done_total = 0
    for _ in range(100):
        ip.run_line_magic("dist_checkpoint", "--status")
        out = capsys.readouterr().out
        done_total += out.count("done")
        if done_total == 2:
            break
        time.sleep(0.1)
    else:
        raise AssertionError(
            f"background save never finished (saw {done_total} done): "
            f"{out}")
    # A second status poll reports idle (the handle was consumed).
    ip.run_line_magic("dist_checkpoint", "--status")
    assert capsys.readouterr().out.count("idle") == 2
    run(ip, "ckbg_v = None")
    capsys.readouterr()
    ip.run_line_magic("dist_restore", str(path))
    capsys.readouterr()
    run(ip, "float(ckbg_v[5])")
    out = capsys.readouterr().out
    assert "5.0" in out and "10.0" in out


def test_dist_logs_shows_worker_stdio(ip, capsys):
    # sys.stderr writes bypass the streaming stdout path and land in
    # the process pipe the manager drains.
    run(ip, "import sys; sys.stderr.write('raw-stderr-marker\\n')")
    import time

    from nbdistributed_tpu.magics.magic import DistributedMagics
    pm = DistributedMagics._pm
    deadline = time.time() + 10
    while time.time() < deadline:  # poll the drain thread, no fixed sleep
        if "raw-stderr-marker" in pm.io[0].tail(400):
            break
        time.sleep(0.05)
    capsys.readouterr()
    ip.run_line_magic("dist_logs", "")
    out = capsys.readouterr().out
    assert "rank 0 stdio" in out and "rank 1 stdio" in out
    assert "raw-stderr-marker" in out


def _dump_worker_stdio():
    """Failure diagnostics: print each worker's captured stdio and
    returncode (how the byte-loss interrupt race was root-caused)."""
    from nbdistributed_tpu.magics.magic import DistributedMagics
    pm = DistributedMagics._pm
    if pm is None:
        return
    for r, io in pm.io.items():
        print(f"==== rank {r} rc={pm.processes[r].poll()} ====")
        print(io.tail(30))


def test_dist_interrupt_magic_idle(ip, capsys):
    ip.run_line_magic("dist_interrupt", "")
    out = capsys.readouterr().out
    assert "interrupt sent to ranks [0, 1]" in out
    run(ip, "'post-interrupt-alive'")
    out = capsys.readouterr().out
    if "post-interrupt-alive" not in out:
        _dump_worker_stdio()
    assert "post-interrupt-alive" in out


def test_collective_subset_runtime_guard(ip, capsys):
    """ACTUALLY calling a world-collective from a subset cell — via an
    alias the pre-flight regex cannot see — must surface a prompt
    per-rank error (the runtime guard raises at CALL time,
    runtime/collective_guard.py) instead of deadlocking the mesh."""
    import time

    run(ip, "alias_fn = all_reduce")       # full mesh: bind, no call
    capsys.readouterr()
    t0 = time.time()
    run(ip, "%%rank [0]\nalias_fn(1.0)")   # no collective token here
    dt = time.time() - t0
    out = capsys.readouterr().out
    assert "strict subset" in out and "deadlock" in out, out
    assert dt < 60, f"guard should raise instantly, took {dt:.0f}s"
    # The mesh survived: both ranks still answer.
    run(ip, "'alive-' + str(rank)")
    out = capsys.readouterr().out
    assert "alive-0" in out and "alive-1" in out


def test_collective_full_mesh_still_works_and_counts(ip, capsys):
    """Full-mesh collectives keep working under the guard, and the
    coordinator records the cell's rank coverage from the
    worker-reported hash."""
    run(ip, "full_sum = all_reduce(rank + 1.0)\nfloat(full_sum)")
    out = capsys.readouterr().out
    assert "3.0" in out                    # (0+1) + (1+1)
    from nbdistributed_tpu.magics.magic import DistributedMagics
    from nbdistributed_tpu.runtime import collective_guard
    # The auto-distribute transformer ships the cell with a trailing
    # newline; the worker hashes exactly what it executed.
    h = collective_guard.cell_hash(
        "full_sum = all_reduce(rank + 1.0)\nfloat(full_sum)\n")
    assert DistributedMagics._cell_rank_history.get(h) == {0, 1}


def test_timeline_sidecar_flushes_and_hook_embeds(ip, capsys, tmp_path):
    """%timeline_sidecar on <nb> auto-writes the sidecar after each
    cell; the server pre_save_hook folds it into notebook metadata —
    the in-.ipynb persistence path end-to-end."""
    import json

    from nbdistributed_tpu import jupyter_hooks as jh

    nb = tmp_path / "session.ipynb"
    nb.write_text("{}")
    ip.run_line_magic("timeline_sidecar", f"on {nb}")
    capsys.readouterr()
    run(ip, "sidecar_probe = rank + 40\nsidecar_probe")
    capsys.readouterr()
    sc = jh.sidecar_path(str(nb))
    payload = json.loads(open(sc).read())
    assert any("sidecar_probe" in r["code"] for r in payload["records"])
    model = {"type": "notebook", "content": {"metadata": {}}}
    jh.pre_save_hook(model=model, path=str(nb))
    assert model["content"]["metadata"][jh.METADATA_KEY]["records"]
    ip.run_line_magic("timeline_sidecar", "off")
    capsys.readouterr()


def test_dist_trace_magic_records_and_saves(ip, capsys, tmp_path):
    """%dist_trace start → traced cell → save: the merged Chrome-trace
    file carries coordinator AND both ranks' spans, and the timeline
    record of the traced cell carries the cell span's ids."""
    import json

    from nbdistributed_tpu.magics.magic import DistributedMagics

    ip.run_line_magic("dist_trace", "start")
    out = capsys.readouterr().out
    assert "tracing ON" in out
    run(ip, "traced_v = rank * 3\ntraced_v")
    capsys.readouterr()
    # Let an IDLE heartbeat land (2 s cadence): %dist_status skips the
    # get_status probe for ranks whose last ping carried busy state,
    # and the per-rank tracing marker rides that probe.
    import time as _time
    _time.sleep(2.5)
    ip.run_line_magic("dist_status", "")
    out = capsys.readouterr().out
    assert "span trace active" in out
    assert "📡 tracing (" in out  # per-rank marker from get_status
    ip.run_line_magic("dist_trace", "status")
    out = capsys.readouterr().out
    assert "tracing ON" in out and "rank 0" in out
    path = tmp_path / "magic_trace.json"
    ip.run_line_magic("dist_trace", f"save {path}")
    out = capsys.readouterr().out
    assert "events →" in out and "perfetto" in out
    trace = json.loads(path.read_text())
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in spans} >= {-1, 0, 1}
    names = {e["name"] for e in spans}
    assert "cell/distributed" in names and "handle/execute" in names \
        and "cell" in names
    # the timeline record of the traced cell names its span
    rec = next(r for r in DistributedMagics._timeline.records
               if "traced_v" in r.code and r.kind == "distributed")
    assert rec.span_id is not None
    assert any(e["args"].get("span_id") == rec.span_id for e in spans)
    ip.run_line_magic("dist_trace", "stop")
    out = capsys.readouterr().out
    assert "tracing OFF" in out


def test_dist_metrics_magic_reports(ip, capsys, tmp_path):
    import json

    ip.run_line_magic("dist_metrics", "")
    out = capsys.readouterr().out
    assert "coordinator: wire" in out
    assert "rank 0: cells" in out and "rank 1: cells" in out
    path = tmp_path / "metrics.json"
    ip.run_line_magic("dist_metrics", f"--save {path}")
    capsys.readouterr()
    snap = json.loads(path.read_text())
    assert "coordinator" in snap and set(snap["ranks"]) == {"0", "1"}
    assert any(k.startswith("nbd_wire_messages_total")
               for k in snap["ranks"]["0"]["counters"])
    ip.run_line_magic("dist_metrics", "--prom")
    out = capsys.readouterr().out
    assert "# TYPE nbd_wire_messages_total counter" in out


def test_profile_handler_idempotent(ip, tmp_path):
    """Satellite of ISSUE 2: stop-without-start and double-start reply
    with clear {status, error} instead of crashing the handler, and
    stop reports the directory the trace was STARTED with."""
    from nbdistributed_tpu.magics.magic import DistributedMagics

    comm = DistributedMagics._comm
    resp = comm.send_to_all("profile", {"action": "stop"}, timeout=60)
    for m in resp.values():
        assert m.data["status"] == "idle"
        assert "no profiler trace" in m.data["error"]
    d1 = str(tmp_path / "prof1")
    resp = comm.send_to_all("profile", {"action": "start",
                                        "log_dir": d1}, timeout=60)
    started = {r: m.data for r, m in resp.items()}
    ok = all(d["status"] == "profiling" and "error" not in d
             for d in started.values())
    if ok:
        # second start: clear error, original dir reported
        resp = comm.send_to_all("profile", {"action": "start",
                                            "log_dir": "/tmp/other"},
                                timeout=60)
        for r, m in resp.items():
            assert "already running" in m.data["error"]
            assert m.data["log_dir"] == started[r]["log_dir"]
        # stop reports the ACTUAL start dir, not the stop message's
        resp = comm.send_to_all("profile", {"action": "stop",
                                            "log_dir": "/tmp/bogus"},
                                timeout=60)
        for r, m in resp.items():
            assert m.data["status"] == "stopped"
            assert m.data["log_dir"] == started[r]["log_dir"]
    # and a second stop is clean either way
    resp = comm.send_to_all("profile", {"action": "stop"}, timeout=60)
    for m in resp.values():
        assert m.data["status"] == "idle"


def test_dist_chaos_and_supervise_magics(ip, capsys):
    """Notebook surface of the resilience stack: %dist_chaos arms /
    reports / clears fault plans on both sides (duplicate-only, so the
    un-retried magics channel stays reliable — dedup absorbs the
    dups), and %dist_supervise attaches, surfaces in %dist_status, and
    stops.  The heavy kill-and-heal path is covered in
    test_chaos_heal.py."""
    ip.run_line_magic("dist_chaos", "on --duplicate 0.5 --seed 7")
    out = capsys.readouterr().out
    assert "chaos ON" in out
    run(ip, "chaos_v = rank + 1\nchaos_v")
    out = capsys.readouterr().out
    assert "Rank 0" in out and "Rank 1" in out  # cells still exact
    ip.run_line_magic("dist_chaos", "status")
    out = capsys.readouterr().out
    assert "rank 0" in out and "counters=" in out
    ip.run_line_magic("dist_chaos", "off")
    out = capsys.readouterr().out
    assert "chaos off" in out
    ip.run_line_magic("dist_supervise", "on --max-restarts 2")
    out = capsys.readouterr().out
    assert "supervising 2 workers" in out
    ip.run_line_magic("dist_status", "")
    out = capsys.readouterr().out
    assert "supervisor" in out and "alive" in out
    ip.run_line_magic("dist_supervise", "status")
    out = capsys.readouterr().out
    assert "restarts 0/2" in out
    ip.run_line_magic("dist_supervise", "off")
    out = capsys.readouterr().out
    assert "supervisor stopped" in out


def test_status_shows_durable_session_header(ip, capsys):
    """%dist_status names the run dir, token fingerprint, epoch, and
    the orphan-capable state of a durable session (ISSUE 4)."""
    ip.run_line_magic("dist_status", "")
    out = capsys.readouterr().out
    assert "session: run " in out
    assert "epoch 1" in out
    assert "orphan-capable" in out
    assert "token" in out


def test_session_manifest_written_by_init(ip):
    """%dist_init persisted an adoptable manifest under NBD_RUN_DIR:
    live pids, the live control port, epoch 1, a token."""
    import os

    from nbdistributed_tpu.magics.magic import DistributedMagics
    from nbdistributed_tpu.resilience import session

    m = session.read_manifest(os.environ["NBD_RUN_DIR"])
    assert m is not None
    assert m["world_size"] == 2
    assert m["control"]["port"] == DistributedMagics._comm.port
    assert sorted(session.live_pids(m)) == [0, 1]
    assert m["epoch"] == 1 and m["token"]
    assert m["init_line"] == DistributedMagics._last_init_line


def test_dist_gc_magic_sweeps_stale_runs(ip, capsys, tmp_path):
    """%dist_gc --dry-run lists but keeps; the real run removes only
    the stale sibling (old manifest, dead pid)."""
    import os
    import time as _time

    from nbdistributed_tpu.resilience import session

    root = str(tmp_path / "runs")
    d = os.path.join(root, "run-dead")
    session.write_manifest(d, session.make_manifest(
        world_size=1, control_host="127.0.0.1", control_port=1,
        token="t", epoch=1, pids={0: 2 ** 22 + 7}))
    old = _time.time() - 7200
    os.utime(session.manifest_path(d), (old, old))
    ip.run_line_magic("dist_gc", f"--dry-run --ttl 3600 --root {root}")
    out = capsys.readouterr().out
    assert "would sweep 1" in out and os.path.isdir(d)
    ip.run_line_magic("dist_gc", f"--ttl 3600 --root {root}")
    out = capsys.readouterr().out
    assert "swept 1" in out and not os.path.exists(d)


def test_dist_heal_respawns_and_restores(ip, capsys, tmp_path):
    """Elastic recovery (SURVEY §5.3): kill a worker hard, %dist_heal
    rebuilds the world with the remembered %dist_init config and
    restores the checkpoint — the session continues where it saved.
    Runs LAST-ish in this module: it replaces the fixture's cluster
    with an identical fresh one."""
    import time as _time

    from nbdistributed_tpu.magics.magic import DistributedMagics

    run(ip, "heal_v = jnp.arange(3.0) + rank")
    capsys.readouterr()
    ip.run_line_magic("dist_checkpoint", f"{tmp_path}/heal_ck heal_v")
    capsys.readouterr()

    # All alive: heal is a no-op without --force.
    ip.run_line_magic("dist_heal", "")
    out = capsys.readouterr().out
    assert "nothing to heal" in out

    DistributedMagics._pm.processes[1].kill()       # hard crash
    deadline = _time.time() + 30
    while _time.time() < deadline:
        if 1 not in set(DistributedMagics._pm.alive_ranks()):
            break
        _time.sleep(0.2)
    else:
        raise AssertionError("worker 1 death not detected")

    ip.run_line_magic("dist_heal", f"--restore {tmp_path}/heal_ck")
    out = capsys.readouterr().out
    assert "healing: dead ranks [1]" in out, out
    assert "workers ready" in out                   # world is back
    assert DistributedMagics._world == 2
    run(ip, "print('healed', rank, float(heal_v.sum()))")
    out = capsys.readouterr().out
    assert "healed 0 3.0" in out                    # 0+1+2 restored
    assert "healed 1 6.0" in out                    # 1+2+3 restored


def test_watchdog_and_doctor_magics(ip, capsys):
    """%dist_watchdog lifecycle + %dist_doctor on a healthy mesh (the
    hang-breaking acceptance path lives in test_hang_watchdog.py; the
    magic surface is what this covers): auto-armed at init, status,
    reconfigure with knobs, a ladder typo is rejected, the doctor's
    report renders positions and 'verdicts: none', and --deadline
    rides a %%distributed cell without tripping a healthy run."""
    from nbdistributed_tpu.magics.magic import DistributedMagics

    # Auto-armed by the fixture's %dist_init (NBD_HANG defaults on).
    assert DistributedMagics._watchdog is not None
    ip.run_line_magic("dist_watchdog", "status")
    out = capsys.readouterr().out
    assert "hang watchdog" in out and "ladder" in out

    ip.run_line_magic("dist_watchdog",
                      "on --skew 7 --stall 44 --escalate warn,interrupt")
    out = capsys.readouterr().out
    assert "hang watchdog ON" in out
    assert "skew 7s" in out and "stall 44s" in out
    assert "warn→interrupt" in out
    pol = DistributedMagics._watchdog.policy
    assert (pol.skew_s, pol.stall_s) == (7.0, 44.0)

    ip.run_line_magic("dist_watchdog", "on --escalate warn,dmup")
    out = capsys.readouterr().out
    assert "unknown escalation" in out

    # A generous deadline on a fast cell: runs clean, no verdict.
    run(ip, "%%distributed --deadline 300\ndl_ok = rank + 40\ndl_ok")
    out = capsys.readouterr().out
    assert "40" in out and "41" in out
    assert DistributedMagics._watchdog.cells_flagged == 0

    run(ip, "import jax.numpy as jnp\n"
            "wd_v = float(all_reduce(jnp.ones(2))[0])\nwd_v")
    capsys.readouterr()
    # The collective position rides the NEXT heartbeat (2 s cadence) —
    # wait for it so the doctor/top assertions see the piggyback.
    import time as _time
    deadline = _time.time() + 15
    while _time.time() < deadline:
        pings = [DistributedMagics._comm.last_ping(r) for r in (0, 1)]
        if all(p is not None and p[1].get("col") for p in pings):
            break
        _time.sleep(0.3)
    else:
        raise AssertionError("collective piggyback never arrived")
    ip.run_line_magic("dist_doctor", "--no-stacks")
    out = capsys.readouterr().out
    assert "stuck-cell doctor" in out
    assert "verdicts: none" in out
    assert "col#" in out

    # %dist_top renders the collective-seq column from the piggyback.
    ip.run_line_magic("dist_top", "")
    out = capsys.readouterr().out
    assert "col#" in out and "#1" in out

    ip.run_line_magic("dist_watchdog", "off")
    out = capsys.readouterr().out
    assert "stopped" in out
    assert DistributedMagics._watchdog is None
    ip.run_line_magic("dist_watchdog", "status")
    assert "not running" in capsys.readouterr().out


def test_dist_lint_strict_blocks_hazardous_cell(ip, capsys):
    # The PR 5 frozen-rank cell shape, caught BEFORE dispatch: under
    # strict vetting the cell never ships, so the live fleet cannot
    # deadlock (no watchdog/interrupt needed to clean up after it).
    from nbdistributed_tpu.magics.magic import DistributedMagics
    ip.run_line_magic("dist_lint", "strict")
    capsys.readouterr()
    run(ip, "%%distributed\n"
            "import jax.numpy as jnp\n"
            "if rank == 0:\n"
            "    _hz = all_reduce(jnp.ones(1))\n"
            "'hz-done'")
    out = capsys.readouterr().out
    assert "rank-conditional-collective" in out
    assert "NOT dispatched" in out
    assert "hz-done" not in out
    ip.run_line_magic("dist_lint", "warn")
    capsys.readouterr()
    assert DistributedMagics._lint_mode == "warn"


def test_distributed_strict_flag_blocks_one_cell(ip, capsys):
    run(ip, "%%distributed --strict\n"
            "if rank == 1:\n"
            "    _hz2 = barrier()\n"
            "'hz2-done'")
    out = capsys.readouterr().out
    assert "NOT dispatched" in out and "hz2-done" not in out
    # The flag is per-cell: the next plain cell dispatches normally.
    run(ip, "%%distributed\nlint_ok = rank + 70\nlint_ok")
    out = capsys.readouterr().out
    assert "70" in out and "71" in out


def test_dist_lint_warn_annotates_but_dispatches(ip, capsys):
    # Warning-severity finding (host sync in a loop): annotated inline,
    # cell still runs on every rank.
    run(ip, "%%distributed\n"
            "for _li in range(2):\n"
            "    print(_li * rank)\n"
            "'warn-done'")
    out = capsys.readouterr().out
    assert "host-sync-in-loop" in out
    assert "warn-done" in out


def test_dist_lint_status_counts_findings(ip, capsys):
    ip.run_line_magic("dist_lint", "status")
    out = capsys.readouterr().out
    assert "cell vetting: warn" in out
    assert "rank-conditional-collective" in out  # counted earlier
