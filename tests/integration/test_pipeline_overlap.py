"""Integration: the async pipelined executor on a real 2-rank CPU
world (ISSUE 14 acceptance).

Pins the three tentpole behaviors end to end:

* **overlap** — k=4 independent proven-collective-free cells, two per
  rank, complete in < 0.6× the serial wall-clock (each rank's serial
  loop runs its own two cells while the other rank runs its two —
  max, not sum, of the critical paths);
* **ordering** — a RAW-dependent chain streamed through the window
  executes in exact program order (the DAG gate serializes it);
* **--repeat discipline** — a k-step loop is ONE dispatch: per-step
  progress is observed via heartbeat ``rep`` piggybacks while it
  runs, and a redelivered request (same msg_id) is answered from the
  replay cache without re-running a single step.
"""

import time

import pytest

from nbdistributed_tpu.analysis import infer_effects
from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
from nbdistributed_tpu.messaging import CommunicationManager
from nbdistributed_tpu.messaging.pipeline import AsyncExecutor

pytestmark = [pytest.mark.integration, pytest.mark.pipeline,
              pytest.mark.slow]

WORLD = 2
ATTACH_TIMEOUT = 120


@pytest.fixture(scope="module")
def cluster():
    comm = CommunicationManager(num_workers=WORLD, timeout=60)
    pm = ProcessManager()
    pm.add_death_callback(lambda rank, rc: comm.mark_worker_dead(rank))
    try:
        pm.start_workers(WORLD, comm.port, backend="cpu")
        wait_until_ready(comm, pm, ATTACH_TIMEOUT)
    except Exception:
        pm.shutdown()
        comm.shutdown()
        raise
    yield comm, pm
    comm.post(list(range(WORLD)), "shutdown")
    time.sleep(0.5)
    pm.shutdown()
    comm.shutdown()


def fp(code):
    return infer_effects(code).as_dict()


SLEEP_S = 0.3


def _sleep_cell(i):
    # `time` is a proven-safe stdlib module and only READ here (the
    # import happens once, in setup — an in-cell `import time` would
    # WRITE the name and draw a WAW edge between every pair, which
    # the gate would rightly serialize): the footprint is
    # collective-free with disjoint writes, so the window may overlap
    # these across ranks.
    return f"time.sleep({SLEEP_S})\npipe_overlap_{i} = {i}"


def test_independent_cells_overlap_below_serial(cluster):
    """k=4 proven-free cells, two aimed at each rank: serial dispatch
    pays sum-of-sleeps; the async window pays ~max per rank."""
    comm, _ = cluster
    comm.send_to_all("execute", "import time", timeout=60)
    cells = [( _sleep_cell(i), [i % WORLD]) for i in range(4)]

    # Serial baseline: send-and-wait per cell, same cells.
    t0 = time.perf_counter()
    for code, ranks in cells:
        comm.send_to_ranks(ranks, "execute",
                           {"code": code, "target_ranks": ranks},
                           timeout=60)
    serial_s = time.perf_counter() - t0
    assert serial_s >= 4 * SLEEP_S  # sanity: the sleeps are real

    ex = AsyncExecutor(comm, window=4)
    t0 = time.perf_counter()
    futs = [ex.submit_cell(code, ranks, entry=fp(code))
            for code, ranks in cells]
    ex.drain()
    async_s = time.perf_counter() - t0

    assert all(f.state == "done" for f in futs), \
        [(f.seq, f.state, str(f.error)) for f in futs]
    assert ex.depth == 0
    # The acceptance bar: < 0.6x serial wall-clock.  Two ranks x two
    # sleeps each run concurrently, so the floor is ~2*SLEEP_S
    # against a ~4*SLEEP_S serial baseline.
    assert async_s < 0.6 * serial_s, \
        f"async {async_s:.3f}s vs serial {serial_s:.3f}s"


def test_raw_dependent_chain_executes_in_program_order(cluster):
    """A RAW chain streamed through the window must serialize: each
    cell appends to a worker-side list, and the final list IS the
    program order."""
    comm, _ = cluster
    ranks = list(range(WORLD))
    ex = AsyncExecutor(comm, window=4)
    first = "pipe_order = [0]"
    futs = [ex.submit_cell(first, ranks, entry=fp(first))]
    for i in range(1, 4):
        code = f"pipe_order = pipe_order + [{i}]"
        futs.append(ex.submit_cell(code, ranks, entry=fp(code)))
    ex.drain()
    assert all(f.state == "done" for f in futs), \
        [(f.seq, f.state, str(f.error)) for f in futs]
    # The chain held at the gate at least once (RAW on pipe_order).
    assert ex.snapshot()["held_total"] >= 1
    out = comm.send_to_all("execute", "pipe_order", timeout=60)
    assert {r: m.data.get("output") for r, m in out.items()} == {
        0: "[0, 1, 2, 3]", 1: "[0, 1, 2, 3]"}


def test_repeat_is_one_dispatch_with_replay_cache_discipline(cluster):
    """--repeat k: k steps of worker-side state advance under ONE
    msg_id; redelivering that msg_id answers from the replay cache
    and re-runs nothing."""
    comm, _ = cluster
    ranks = list(range(WORLD))
    comm.send_to_all("execute", "pipe_cnt = 0", timeout=60)
    payload = {"code": "pipe_cnt = pipe_cnt + 1\npipe_cnt",
               "target_ranks": ranks, "repeat": 9}
    mid = "pipe-repeat-pinned-1"
    resp = comm.send_to_ranks(ranks, "execute", payload,
                              timeout=120, msg_id=mid)
    for r, m in resp.items():
        assert m.data.get("steps") == 9, m.data
        assert m.data.get("output", "").strip() == "9"
    # Redelivery under the SAME msg_id: the replay cache answers; the
    # counter must not advance (no step re-runs).
    resp2 = comm.send_to_ranks(ranks, "execute", payload,
                               timeout=120, msg_id=mid)
    for r, m in resp2.items():
        assert m.data.get("steps") == 9
    out = comm.send_to_all("execute", "pipe_cnt", timeout=60)
    assert all(m.data.get("output") == "9" for m in out.values())


def test_repeat_reports_per_step_telemetry_via_heartbeats(cluster):
    """While a --repeat loop runs, heartbeat pings carry the `rep`
    piggyback (step index, total, steps/s) — per-step progress with
    one dispatch and no probe through the busy serial loop."""
    comm, _ = cluster
    ranks = list(range(WORLD))
    steps = 60
    payload = {"code": "import time\ntime.sleep(0.12)",
               "target_ranks": ranks, "repeat": steps}
    handle = comm.submit(ranks, "execute", payload, timeout=120)
    seen = {}
    deadline = time.time() + 30
    try:
        while time.time() < deadline and len(seen) < WORLD:
            for r in range(WORLD):
                ping = comm.last_ping(r)
                if ping is None:
                    continue
                rep = (ping[1] or {}).get("rep")
                if rep:
                    seen[r] = dict(rep)
            if handle.done():
                break
            time.sleep(0.1)
    finally:
        resp = handle.wait(120)
    assert seen, "no heartbeat carried the rep piggyback"
    for r, rep in seen.items():
        assert 1 <= rep["i"] <= steps
        assert rep["k"] == steps
        assert rep["sps"] >= 0
    for r, m in resp.items():
        assert m.data.get("steps") == steps
    # The loop finished: the piggyback clears from later pings.
    time.sleep(3)
    for r in range(WORLD):
        ping = comm.last_ping(r)
        assert not (ping[1] or {}).get("rep")


def test_until_stops_early_worker_side(cluster):
    comm, _ = cluster
    ranks = list(range(WORLD))
    payload = {"code": "pipe_u = pipe_u + 1 if 'pipe_u' in globals() "
                       "else 1",
               "target_ranks": ranks, "repeat": 100,
               "until": "pipe_u >= 5"}
    resp = comm.send_to_all("execute", payload, timeout=120)
    for m in resp.values():
        assert m.data.get("steps") == 5
        assert m.data.get("stopped_early") is True


def test_error_future_surfaces_after_drain(cluster):
    comm, _ = cluster
    ranks = list(range(WORLD))
    ex = AsyncExecutor(comm, window=2)
    code = "raise ValueError('pipelined boom')"
    fut = ex.submit_cell(code, ranks, entry=fp(code))
    ex.drain()
    assert fut.state == "error"
    with pytest.raises(RuntimeError, match="pipelined boom"):
        fut.result()
