"""Acceptance tests for durable sessions (ISSUE 4).

The scenario the tentpole exists for, end to end on the CPU backend:

1. A **sacrificial coordinator subprocess** brings up a 4-rank fleet,
   seeds the namespace, fires an in-flight cell, and is SIGKILLed
   mid-cell by this test — the kernel-restart failure mode.
2. The test process becomes the **fresh coordinator**: it reattaches
   via the session manifest and asserts (a) every rank's pre-crash
   namespace is intact, (b) the interrupted cell's parked result is
   redelivered exactly once with zero double-execution, and (c) a
   stale coordinator's epoch-stamped frames are rejected without
   executing.
3. A separate fleet with a short ``NBD_ORPHAN_TTL_S`` is orphaned and
   NOT reattached: (d) every worker self-terminates at TTL expiry with
   flight-recorded ``orphan_expired`` events.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
from nbdistributed_tpu.messaging import CommunicationManager
from nbdistributed_tpu.observability import flightrec
from nbdistributed_tpu.resilience import session

pytestmark = [pytest.mark.integration, pytest.mark.faults]

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
COORD1 = os.path.join(REPO_ROOT, "tests", "integration",
                      "_attach_coord.py")
WORLD = 4


def outputs(responses):
    return {r: m.data.get("output") for r, m in responses.items()}


def _kill_manifest_pids(run_dir):
    m = session.read_manifest(run_dir) or {}
    for pid in (m.get("pids") or {}).values():
        try:
            os.kill(int(pid), signal.SIGKILL)
        except (OSError, ValueError):
            pass


def test_coordinator_crash_attach_redeliver_epoch(tmp_path,
                                                  monkeypatch):
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    monkeypatch.setenv("NBD_RUN_DIR", run_dir)
    flightrec.reset_for_tests()

    coord1 = subprocess.Popen(
        [sys.executable, COORD1, run_dir, str(WORLD)],
        cwd=REPO_ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    comm = pm = None
    try:
        # --- phase 1: sacrificial coordinator up, cell in flight -----
        status_path = os.path.join(run_dir, "coord1.json")
        deadline = time.time() + 240
        while not os.path.exists(status_path):
            assert coord1.poll() is None, (
                "coordinator #1 died during bring-up:\n"
                + coord1.stdout.read().decode("utf-8", "replace"))
            assert time.time() < deadline, "coordinator #1 never ready"
            time.sleep(0.2)
        st = json.load(open(status_path))
        fatal_mid = st["fatal_mid"]
        time.sleep(1.0)  # the cell (sleep 4s) is now genuinely mid-flight
        os.kill(coord1.pid, signal.SIGKILL)  # kernel restart, simulated
        coord1.wait()

        # --- phase 2: fresh coordinator reattaches -------------------
        comm, pm, manifest, hello = session.attach(
            run_dir, attach_timeout=120, request_timeout=120)
        assert comm.session_epoch == 2
        assert manifest["epoch"] == 2
        assert manifest["control"]["port"] == comm.port
        assert sorted(hello) == list(range(WORLD))
        for r, h in hello.items():
            assert h.data["status"] == "ok" and h.data["epoch"] == 2
            # the interrupted cell's result is parked on every rank
            assert fatal_mid in h.data["parked"], \
                f"rank {r} parked {h.data['parked']}, not {fatal_mid}"

        # (a) pre-crash namespace intact on all ranks
        out = outputs(comm.send_to_all("execute", "x", timeout=120))
        assert out == {r: "42" for r in range(WORLD)}

        # (b) parked result redelivered exactly once, zero
        # double-execution (the cell incremented `hits` exactly once)
        drained = session.drain_mailboxes(comm)
        for r in range(WORLD):
            assert drained[r][fatal_mid]["output"] == "1", drained[r]
        again = session.drain_mailboxes(comm)
        assert all(not d for d in again.values()), again
        out = outputs(comm.send_to_all("execute", "hits", timeout=120))
        assert out == {r: "1" for r in range(WORLD)}, \
            f"interrupted cell double-executed: {out}"
        stat = comm.send_to_all("mailbox", {"action": "status"},
                                timeout=60)
        for r, m in stat.items():
            c = m.data["counters"]
            assert c["parked"] >= 1 and c["claimed"] >= 1
            assert not m.data["parked"]
        # dedup counters prove redelivery never re-ran anything
        gs = comm.send_to_all("get_status", timeout=60)
        for r, m in gs.items():
            assert m.data["session_epoch"] == 2
            assert m.data["mailbox_parked"] == 0

        # (c) a stale coordinator's frames are rejected by epoch and
        # do NOT execute
        comm.session_epoch = 1  # impersonate the dead coordinator
        try:
            resp = comm.send_to_all("execute", "x = 'clobbered'",
                                    timeout=60)
        finally:
            comm.session_epoch = 2
        for r, m in resp.items():
            assert m.data.get("stale_epoch") is True
            assert "stale coordinator epoch 1" in m.data["error"]
        out = outputs(comm.send_to_all("execute", "x", timeout=120))
        assert out == {r: "42" for r in range(WORLD)}, \
            "stale-epoch execute mutated the namespace"

        # a normal cell still works at the new epoch, end to end
        out = outputs(comm.send_to_all(
            "execute", "y = x + rank\ny", timeout=120))
        assert out == {r: str(42 + r) for r in range(WORLD)}
    finally:
        if coord1.poll() is None:
            coord1.kill()
        if comm is not None:
            try:
                comm.post(list(range(WORLD)), "shutdown")
                time.sleep(0.3)
            except Exception:
                pass
            comm.shutdown()
        if pm is not None:
            pm.shutdown()
        _kill_manifest_pids(run_dir)
        flightrec.reset_for_tests()


def test_orphan_ttl_expiry_self_terminates(tmp_path, monkeypatch):
    run_dir = str(tmp_path / "run")
    monkeypatch.setenv("NBD_RUN_DIR", run_dir)
    flightrec.reset_for_tests()
    world = 2
    comm = CommunicationManager(num_workers=world, timeout=60)
    pm = ProcessManager()
    pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
    try:
        pm.start_workers(world, comm.port, backend="cpu", extra_env={
            "NBD_ORPHAN_TTL_S": "2"})
        wait_until_ready(comm, pm, 120)
        out = outputs(comm.send_to_all("execute", "1 + 1", timeout=60))
        assert out == {0: "2", 1: "2"}
        # Coordinator "dies": the listener closes, nothing ever
        # reattaches, and no teardown signal is sent to the workers.
        pm.quiesce()
        comm.shutdown()
        deadline = time.time() + 40
        while time.time() < deadline:
            if all(p.poll() is not None for p in pm.processes.values()):
                break
            time.sleep(0.25)
        else:
            pytest.fail("orphaned workers did not self-terminate at "
                        "TTL expiry")
        # Clean exits (no signal): the TTL path is a deliberate
        # shutdown, not a crash.
        assert all(p.poll() == 0 for p in pm.processes.values()), \
            {r: p.poll() for r, p in pm.processes.items()}
        # Flight rings narrate the whole orphan lifecycle.
        for r in range(world):
            ring = flightrec.read_latest(run_dir, f"rank{r}")
            assert ring is not None
            kinds = [e.get("t") for e in ring["events"]]
            assert "orphan_entered" in kinds
            assert "orphan_expired" in kinds
            assert "worker_shutdown" in kinds  # clean self-termination
            assert "orphan_reattached" not in kinds
    finally:
        pm.shutdown()
        try:
            comm.shutdown()
        except Exception:
            pass
        flightrec.reset_for_tests()
