"""Integration: the latency observatory over a REAL 2-rank CPU world
(ISSUE 13 acceptance).  A pool cell must yield a complete 8-stage
waterfall whose stages sum to within 10% of the observed end-to-end
latency, the stage histograms must export as parseable Prometheus
text, and turning the observatory off must drop the ``lt`` header
from the wire entirely."""

import time

import pytest

from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
from nbdistributed_tpu.messaging import CommunicationManager
from nbdistributed_tpu.observability.latency import (STAGES,
                                                     format_stage_table,
                                                     format_waterfall)
from nbdistributed_tpu.observability.metrics import \
    validate_prometheus_text

pytestmark = [pytest.mark.integration, pytest.mark.obs]

WORLD = 2
ATTACH_TIMEOUT = 120


@pytest.fixture(scope="module")
def cluster():
    comm = CommunicationManager(num_workers=WORLD, timeout=60)
    pm = ProcessManager()
    pm.add_death_callback(lambda rank, rc: comm.mark_worker_dead(rank))
    try:
        pm.start_workers(WORLD, comm.port, backend="cpu")
        wait_until_ready(comm, pm, ATTACH_TIMEOUT)
    except Exception:
        pm.shutdown()
        comm.shutdown()
        raise
    yield comm, pm
    comm.post(list(range(WORLD)), "shutdown")
    time.sleep(0.5)
    pm.shutdown()
    comm.shutdown()


def test_two_rank_cell_yields_complete_waterfall(cluster):
    comm, _ = cluster
    assert comm.lat.enabled  # NBD_LAT defaults on
    before = len(comm.lat.records())
    t0 = time.time()
    resp = comm.send_to_all("execute", {"code": "rank * 2",
                                        "target_ranks": [0, 1]},
                            vet_s=0.0005)
    wall = time.time() - t0
    assert all(not m.data.get("error") for m in resp.values())

    recs = comm.lat.records()
    assert len(recs) == before + 1
    rec = recs[-1]
    # complete 8-stage waterfall, every stage non-negative
    assert set(rec["stages"]) == set(STAGES)
    assert all(v >= 0.0 for v in rec["stages"].values())
    assert len(rec["ranks"]) == WORLD
    for detail in rec["ranks"].values():
        assert {"wire", "dispatch", "compile", "execute",
                "reply"} <= set(detail)
    # THE acceptance bar: stages sum to within 10% of the observed
    # end-to-end latency
    total = sum(rec["stages"].values())
    assert total == pytest.approx(rec["e2e"], rel=0.10)
    # and the recorded e2e is the latency the caller actually saw
    assert rec["e2e"] <= wall + 0.05
    assert rec["stages"]["vet"] == pytest.approx(0.0005, abs=1e-4)


def test_stage_histograms_export_parseable(cluster):
    comm, _ = cluster
    comm.send_to_all("execute", {"code": "1 + 1",
                                 "target_ranks": [0, 1]})
    from nbdistributed_tpu.observability import metrics as obs_metrics
    text = obs_metrics.registry().prometheus_text()
    assert "# TYPE nbd_stage_seconds histogram" in text
    for s in STAGES:
        assert f'stage="{s}"' in text
    assert "# TYPE nbd_cell_e2e_seconds histogram" in text
    assert validate_prometheus_text(text) == []
    # the %dist_lat renderers work off the live ring
    table = format_stage_table(comm.lat.summary())
    assert "p99" in table and "execute" in table
    assert "█" in format_waterfall(comm.lat.records()[-1:])


def test_lt_header_absent_when_observatory_off(cluster):
    """Flip the observatory off: requests carry no `lt` flag, the live
    workers therefore send stampless replies, and no record lands —
    the absent-when-off wire contract over a real world."""
    comm, _ = cluster
    was = comm.lat.enabled
    comm.lat.enabled = False
    try:
        before = len(comm.lat.records())
        resp = comm.send_to_all("execute", {"code": "3",
                                            "target_ranks": [0, 1]})
        assert all(m.latency is None for m in resp.values())
        assert len(comm.lat.records()) == before
    finally:
        comm.lat.enabled = was
    # back on: stamps flow again on the same connections
    resp = comm.send_to_all("execute", {"code": "4",
                                        "target_ranks": [0, 1]})
    assert all(isinstance(m.latency, dict) for m in resp.values())


def test_clock_offsets_exported_and_sane(cluster):
    """Same-host workers: the estimated offsets must be tiny, and the
    gauges must export (the skew-visibility satellite)."""
    from nbdistributed_tpu.observability import latency as lat_mod
    from nbdistributed_tpu.observability.metrics import MetricsRegistry
    comm, _ = cluster
    stats = comm.clock.stats()
    assert set(stats) == {0, 1}
    for st in stats.values():
        assert abs(st["offset_s"]) < 0.5  # same host, same clock
    reg = MetricsRegistry()
    lat_mod.export_clock_metrics(comm.clock, reg)
    text = reg.prometheus_text()
    assert 'nbd_clock_offset_seconds{rank="0"}' in text
    assert lat_mod.skew_warnings(stats, threshold_ms=5000.0) == []
