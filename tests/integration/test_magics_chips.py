"""%dist_init --chips: the reference's --gpu-ids surface
(reference: magic.py:454-488) on the TPU chip-partitioning contract.

Error paths run before any worker spawns, so these drive a real
IPython shell WITHOUT the module-scoped cluster the e2e tests use.
"""

import pytest

pytestmark = [pytest.mark.integration]


@pytest.fixture()
def shell():
    from IPython.testing.globalipapp import get_ipython, start_ipython

    ip = start_ipython() or get_ipython()
    ip.run_line_magic("load_ext", "nbdistributed_tpu")
    from nbdistributed_tpu.magics.magic import DistributedMagics
    assert DistributedMagics._comm is None, \
        "these tests need a cluster-free shell"
    yield ip
    if DistributedMagics._comm is not None:
        ip.run_line_magic("dist_shutdown", "")


def test_chips_bad_format_rejected_before_spawn(shell, capsys):
    shell.run_line_magic("dist_init", "-n 2 --chips 2,x")
    out = capsys.readouterr().out
    assert "comma-separated integers" in out
    from nbdistributed_tpu.magics.magic import DistributedMagics
    assert DistributedMagics._comm is None


def test_chips_conflicts_with_hosts(shell, capsys):
    shell.run_line_magic(
        "dist_init", "-n 2 --chips 0,1 --hosts local:2")
    out = capsys.readouterr().out
    assert "single-host option" in out
    from nbdistributed_tpu.magics.magic import DistributedMagics
    assert DistributedMagics._comm is None


def test_chips_validation_fails_fast_on_tpu(shell, capsys, monkeypatch):
    """-n 2 with a 1-id list on an (simulated) 4-chip TPU host: the
    pre-spawn validator rejects it with the reference's message."""
    from nbdistributed_tpu.manager import topology

    monkeypatch.setattr(topology, "available_tpu_chips", lambda: 4)
    shell.run_line_magic("dist_init", "-n 2 --backend tpu --chips 3")
    out = capsys.readouterr().out
    assert "Not enough chip IDs" in out
    from nbdistributed_tpu.magics.magic import DistributedMagics
    assert DistributedMagics._comm is None


def test_chips_ignored_on_cpu_backend(shell, capsys):
    """Reference parity ("CUDA not available, GPU IDs will be
    ignored"): a cpu world starts normally, chips dropped."""
    shell.run_line_magic(
        "dist_init", "-n 2 --backend cpu --chips 0,1 "
                     "--attach-timeout 120 -t 60")
    out = capsys.readouterr().out
    assert "chip IDs will be ignored" in out
    from nbdistributed_tpu.magics.magic import DistributedMagics
    assert DistributedMagics._comm is not None   # world came up anyway
    shell.run_line_magic("dist_shutdown", "")
    capsys.readouterr()
