"""Acceptance test for the self-healing control plane (ISSUE 1).

One scripted session against real worker subprocesses under
``JAX_PLATFORMS=cpu``:

1. both control-plane directions drop ~10% of frames (and duplicate a
   few) under FIXED FaultPlan seeds, with redelivery enabled — a
   20-increment counter cell sequence must land on exactly 20 on every
   rank (zero double-executions) and the workers' dedup counters must
   show the replay cache actually absorbed redeliveries;
2. the fault plan SIGKILLs rank 1 mid-cell — the pending request must
   abort with ``WorkerDied`` well inside heartbeat-scale detection,
   never hang;
3. the auto-heal supervisor rebuilds the world and restores the
   checkpointed namespace — the session ends healed: all ranks alive,
   ``counter`` back at 20 from the checkpoint;
4. (ISSUE 3) the supervisor captured a postmortem bundle for the
   killed rank BEFORE healing: the dead rank's flight ring — recovered
   from the file its SIGKILLed process left behind — contains the
   dispatch event of the fatal message id, and the merged Chrome trace
   carries every surviving pid plus the dead rank's recovered events.
"""

import json
import os
import threading
import time

import pytest

from nbdistributed_tpu.manager import ProcessManager, wait_until_ready
from nbdistributed_tpu.messaging import CommunicationManager, WorkerDied
from nbdistributed_tpu.observability import flightrec
from nbdistributed_tpu.resilience import (FaultPlan, RetryPolicy,
                                          Supervisor, SupervisorPolicy)

pytestmark = [pytest.mark.integration, pytest.mark.faults,
              pytest.mark.postmortem]

WORLD = 2
ATTACH_TIMEOUT = 120

# Aggressive redelivery: the chaos run must make progress through 10%
# frame loss without waiting out whole request deadlines.
RETRY = RetryPolicy(attempts=6, attempt_timeout_s=2.0,
                    backoff_base_s=0.1, backoff_max_s=0.5, jitter=0.25)


def _bring_up(extra_env=None):
    comm = CommunicationManager(num_workers=WORLD, timeout=60,
                                retry=RETRY)
    pm = ProcessManager()
    pm.add_death_callback(lambda rank, rc: comm.mark_worker_dead(rank))
    try:
        pm.start_workers(WORLD, comm.port, backend="cpu",
                         extra_env=extra_env)
        wait_until_ready(comm, pm, ATTACH_TIMEOUT)
    except Exception:
        pm.shutdown()
        comm.shutdown()
        raise
    return comm, pm


def outputs(responses):
    return {r: m.data.get("output") for r, m in responses.items()}


def test_chaos_drop_kill_heal_zero_double_executions(tmp_path,
                                                     monkeypatch):
    ckpt = str(tmp_path / "ck")
    # Route every process's flight ring (coordinator + workers inherit
    # the env at spawn) into this test's run dir, and force a FRESH
    # coordinator ring there (an earlier test in this pytest process
    # may have opened one under a different run dir).
    monkeypatch.setenv("NBD_RUN_DIR", str(tmp_path / "run"))
    flightrec.reset_for_tests()
    # Worker-side plan via the env knob (both ranks, fixed seed):
    # drops/duplicates replies and other worker->coordinator frames.
    env = {"NBD_FAULT_PLAN": json.dumps(
        {"seed": 1234, "drop": 0.10, "duplicate": 0.05})}
    box = {}
    box["comm"], box["pm"] = _bring_up(extra_env=env)
    # Coordinator-side plan (offset seed): drops/duplicates requests.
    box["comm"].set_fault_plan(
        FaultPlan(seed=4321, drop=0.10, duplicate=0.05))

    restore_checked = threading.Event()

    def heal():
        """Supervisor heal: tear down the remnants, respawn a CLEAN
        world (chaos is over), restore the checkpoint."""
        old_comm, old_pm = box["comm"], box["pm"]
        try:
            old_pm.shutdown()
        finally:
            old_comm.shutdown()
        comm2, pm2 = _bring_up()
        resp = comm2.send_to_all(
            "checkpoint", {"action": "restore", "path": ckpt,
                           "names": None}, timeout=120)
        assert all(m.data.get("status") == "restore"
                   for m in resp.values()), \
            {r: m.data for r, m in resp.items()}
        restore_checked.set()
        box["comm"], box["pm"] = comm2, pm2
        return comm2, pm2

    sup = Supervisor(SupervisorPolicy(poll_s=0.2, max_restarts=2),
                     heal=heal)
    sup.attach(box["comm"], box["pm"])
    try:
        comm = box["comm"]
        # --- phase 1: lossy link, exact-once execution ---------------
        comm.send_to_all("execute", "counter = 0", timeout=60)
        N = 20
        for _ in range(N):
            comm.send_to_all("execute", "counter += 1", timeout=60)
        out = outputs(comm.send_to_all("execute", "counter", timeout=60))
        assert out == {0: str(N), 1: str(N)}, \
            f"double- or missed executions under chaos: {out}"
        st = comm.send_to_all("get_status", timeout=60)
        dedup = {r: m.data.get("dedup_hits", 0) for r, m in st.items()}
        # The fixed seeds guarantee redeliveries happened; every one
        # must have been answered from the replay cache.
        assert sum(dedup.values()) >= 1, \
            f"chaos run exercised no redelivery (dedup={dedup})"

        # --- phase 2: checkpoint, then SIGKILL rank 1 mid-cell -------
        resp = comm.send_to_all(
            "checkpoint", {"action": "save", "path": ckpt,
                           "names": ["counter"]}, timeout=120)
        assert all(m.data.get("status") == "save"
                   for m in resp.values())
        # Arm the kill via the runtime chaos channel: rank 1 dies on
        # the NEXT message it receives — i.e. mid-cell from the
        # coordinator's point of view.
        comm.send_to_all("chaos", {"action": "set",
                                   "spec": {"kill_rank": 1,
                                            "kill_at": 1}}, timeout=60)
        t0 = time.time()
        with pytest.raises(WorkerDied) as died:
            comm.send_to_all("execute", "'doomed'", timeout=60)
        detect_s = time.time() - t0
        # The aborted request's id — the postmortem must find its
        # dispatch event in the DEAD rank's recovered flight ring.
        fatal_id = died.value.msg_id
        assert fatal_id, "WorkerDied did not carry the aborted msg_id"
        assert detect_s < 30, \
            f"death detection took {detect_s:.1f}s (heartbeat-scale " \
            f"expected)"

        # --- phase 3: auto-heal -------------------------------------
        deadline = time.time() + 180
        while time.time() < deadline:
            s = sup.status()
            if s["heals_done"] >= 1 and sup.healthy():
                break
            assert s["heals_failed"] == 0, s
            time.sleep(0.25)
        else:
            pytest.fail(f"world never healed: {sup.status()}")
        assert restore_checked.is_set()
        comm2 = box["comm"]
        assert box["pm"].alive_ranks() == [0, 1]
        out = outputs(comm2.send_to_all("execute", "counter",
                                        timeout=60))
        assert out == {0: str(N), 1: str(N)}, \
            f"namespace not restored from checkpoint: {out}"
        # transitions surfaced: dead -> healing -> alive for rank 1
        kinds = [(e["rank"], e["to"]) for e in sup.status()["events"]]
        assert (1, "dead") in kinds and (1, "healing") in kinds \
            and (1, "alive") in kinds

        # --- phase 4: postmortem bundle for the killed rank ----------
        manifest = sup.last_postmortem
        assert manifest is not None, \
            "supervisor healed without capturing a postmortem"
        assert manifest["dead_ranks"] == [1]
        bundle = manifest["dir"]
        # The dead rank's ring, recovered from the SIGKILLed process's
        # file, names the fatal message: its dispatch event was
        # recorded BEFORE the injected kill fired.
        ring1 = json.load(open(os.path.join(bundle,
                                            "flight_rank1.json")))
        assert any(e.get("t") == "dispatch"
                   and e.get("msg_id") == fatal_id
                   for e in ring1["events"]), \
            f"fatal dispatch {fatal_id} missing from recovered ring"
        # ...and its last recorded act is that dispatch (nothing after
        # the kill), preceded by the same chaos-phase history the live
        # ranks saw (cell events from phase 1).
        assert ring1["events"][-1]["t"] == "dispatch"
        assert any(e["t"] == "cell_start" for e in ring1["events"])
        # Merged Chrome trace: all surviving pids plus the dead rank's
        # recovered events on one timeline.
        trace = json.load(open(os.path.join(bundle, "trace.json")))
        flight = [e for e in trace["traceEvents"]
                  if e.get("cat") == "flight"]
        assert {e["pid"] for e in flight} >= {-1, 0, 1}
        assert any(e["pid"] == 1
                   and e["args"].get("msg_id") == fatal_id
                   for e in flight)
        # Human-readable report names the casualty.
        report = open(os.path.join(bundle, "report.txt")).read()
        assert "rank 1 [DEAD]" in report
    finally:
        sup.stop()
        try:
            box["comm"].post(list(range(WORLD)), "shutdown")
            time.sleep(0.3)
        except Exception:
            pass
        box["pm"].shutdown()
        box["comm"].shutdown()
