"""Acceptance test for effects-aware concurrent scheduling (ISSUE 9),
end to end on the CPU backend: two tenants on one pool with
``mesh_slots=2`` and effects admission armed.

The scenario the tentpole exists for:

1. Tenant A runs a long **collective-bearing** cell (an ``all_reduce``
   followed by a sleep) — proven ``bearing`` by the effect analyzer.
2. While it holds the mesh, tenant B's **proven collective-free** cell
   is admitted to the second slot with NO queue notice (the overlap
   the proof gate exists to allow) and completes; no hang-watchdog
   verdict fires.
3. A second **collective-bearing** cell submitted in the same window
   is SERIALIZED with an explicit verdict naming the reason
   (``serialized: collective-bearing …``), then completes once A's
   cell releases the mesh.
4. An **unknown-footprint** cell (a call the analyzer cannot vet)
   serializes too, with the canonical ``collective footprint
   unknown`` reason.

Counters: ``nbd_effects_proven_total``/``nbd_effects_unknown_total``
count classifications, ``nbd_effects_serialized_total`` the held
cells; the scheduler snapshot mirrors the serialization count.

Marked ``slow`` like the other pool scenarios: spin-up is the
timing-sensitive part tier-1 must not absorb; the CI resilience job
owns these (marker ``gateway``).
"""

import threading
import time

import pytest

from nbdistributed_tpu.gateway.client import TenantClient
from nbdistributed_tpu.gateway.daemon import GatewayDaemon
from nbdistributed_tpu.gateway.scheduler import SchedPolicy
from nbdistributed_tpu.observability import flightrec
from nbdistributed_tpu.observability import metrics as obs_metrics

pytestmark = [pytest.mark.integration, pytest.mark.gateway,
              pytest.mark.slow]

WORLD = 2

BEARING_LONG = (
    "import time\n"
    "r1 = all_reduce(jnp.ones(2))\n"
    "time.sleep(4.0)\n"
    "float(r1[0])\n"
)
FREE_CELL = "zz = 40 + 2\nzz"
BEARING_SHORT = "r2 = all_reduce(jnp.ones(2))\nfloat(r2[0])"
UNKNOWN_CELL = "helper = getattr(np, 'sum')\nfloat(helper(np.ones(2)))"


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    """A 2-rank pool with TWO mesh slots and effects admission — the
    configuration the PR 8 hazard paragraph said was unusable without
    proof."""
    import os
    run_dir = str(tmp_path_factory.mktemp("fxpool"))
    old_env = os.environ.get("NBD_RUN_DIR")
    os.environ["NBD_RUN_DIR"] = run_dir
    flightrec.reset_for_tests()
    gw = GatewayDaemon(
        WORLD, backend="cpu",
        policy=SchedPolicy("fair", mesh_slots=2, tenant_inflight=8,
                           queue_depth=16, effects=True),
        request_timeout=None, attach_timeout=240.0)
    try:
        yield gw
    finally:
        gw.close()
        if old_env is None:
            os.environ.pop("NBD_RUN_DIR", None)
        else:
            os.environ["NBD_RUN_DIR"] = old_env


def attach(pool, name, **kw):
    return TenantClient(pool.tenant_host, pool.tenant_port, name,
                        pool_token=pool.pool_token, **kw)


def _wait_active(pool, n, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pool.comm.scheduler.snapshot()["active"] >= n:
            return True
        time.sleep(0.05)
    return False


def test_free_cell_overlaps_bearing_cell_and_bearing_serializes(pool):
    reg = obs_metrics.registry()
    ser_before = pool.comm.scheduler.snapshot()[
        "effects_serialized_total"]
    a = attach(pool, "A")
    b = attach(pool, "B")
    results: dict = {}
    errors: list = []
    free_notices: list = []
    bearing_notices: list = []

    def run(key, client, code, notices):
        try:
            results[key] = client.execute(
                code, on_queued=notices.append)
        except Exception as e:              # noqa: BLE001
            errors.append((key, e))

    try:
        ta = threading.Thread(target=run,
                              args=("a", a, BEARING_LONG, []))
        ta.start()
        # A's bearing cell must hold a mesh slot before the window
        # assertions mean anything.
        assert _wait_active(pool, 1), "A's cell never went active"

        # The serialization: a second bearing cell is held with a
        # verdict naming the reason, even though the second mesh slot
        # is free.
        tc = threading.Thread(
            target=run, args=("b2", b, BEARING_SHORT,
                              bearing_notices))
        tc.start()
        deadline = time.time() + 10
        while time.time() < deadline and not bearing_notices:
            time.sleep(0.05)
        assert bearing_notices, \
            "second bearing cell was never queued with a notice"
        assert any((n.get("reason") or "").startswith("serialized:")
                   for n in bearing_notices), bearing_notices

        # The overlap: B's proven-free cell PROMOTES AROUND the held
        # bearing cell into the free slot while A's cell is still
        # running — 2 active (A + free) with the bearing cell still
        # queued is the scheduler-level proof.
        tb = threading.Thread(
            target=run, args=("b", b, FREE_CELL, free_notices))
        tb.start()
        overlapped = False
        deadline = time.time() + 10
        while time.time() < deadline and not overlapped:
            snap = pool.comm.scheduler.snapshot()
            overlapped = (snap["active"] == 2
                          and snap["queued"] >= 1)
            time.sleep(0.05)
        assert overlapped, pool.comm.scheduler.snapshot()

        for t in (ta, tb, tc):
            t.join(timeout=90)
        assert not errors, errors
        assert results["a"]["status"] == "ok", results["a"]
        assert results["b"]["status"] == "ok", results["b"]
        assert results["b2"]["status"] == "ok", results["b2"]
        # The free cell was never effects-serialized — any notice it
        # got was plain backpressure, not a proof refusal.
        assert not any((n.get("reason") or "").startswith(
            "serialized:") for n in free_notices), free_notices

        # Both completed with ZERO hang-watchdog verdicts: the
        # overlap was provably safe.
        st = pool.status()
        assert not st.get("hang_verdicts"), st["hang_verdicts"]

        snap = pool.comm.scheduler.snapshot()
        assert snap["effects_serialized_total"] >= ser_before + 1
        assert reg.counter(
            "nbd_effects_proven_total",
            labels={"footprint": "free"}).value >= 1
        assert reg.counter(
            "nbd_effects_proven_total",
            labels={"footprint": "bearing"}).value >= 2
        assert reg.counter(
            "nbd_effects_serialized_total",
            labels={"tenant": "B"}).value >= 1
    finally:
        a.close(detach=True)
        b.close(detach=True)


def test_unknown_footprint_serializes_with_canonical_reason(pool):
    reg = obs_metrics.registry()
    a = attach(pool, "A2")
    b = attach(pool, "B2")
    results: dict = {}
    errors: list = []
    notices: list = []
    try:
        ta = threading.Thread(target=lambda: results.update(
            a_res=a.execute(BEARING_LONG)))
        ta.start()
        assert _wait_active(pool, 1)

        def run_unknown():
            try:
                results["u"] = b.execute(UNKNOWN_CELL,
                                         on_queued=notices.append)
            except Exception as e:          # noqa: BLE001
                errors.append(e)

        tu = threading.Thread(target=run_unknown)
        tu.start()
        deadline = time.time() + 10
        while time.time() < deadline and not notices:
            time.sleep(0.05)
        assert notices and "collective footprint unknown" in \
            (notices[0].get("reason") or ""), notices

        ta.join(timeout=90)
        tu.join(timeout=90)
        assert not errors, errors
        assert results["u"]["status"] == "ok", results["u"]
        assert reg.counter("nbd_effects_unknown_total").value >= 1
    finally:
        a.close(detach=True)
        b.close(detach=True)
