"""Orchestrator for the two-network-namespace scenario — the REAL-link
variant of the multihost acceptance test.

Runs under ``unshare -Urn`` (root inside a fresh user+net namespace):

1. builds a veth pair and moves one end into a SECOND net namespace
   (the "far host") running :mod:`_netns_far`'s HostAgent — the link
   between coordinator and far host is now an actual veth device, not
   loopback;
2. launches a 2-rank world: rank 0 a direct child, rank 1 spawned via
   the agent across the veth (authenticated NBDA preamble over
   10.99.0.0/24);
3. runs a cell on both ranks and checks streamed stdout crossed the
   link;
4. **downs the veth** — a real network partition, no fault plan — and
   asserts the supervisor's partition sentry flags hostB as SUSPECTED
   without healing;
5. **ups the veth** and asserts suspicion clears, both ranks serve
   again, and zero heals happened end to end.

Writes ``result.json`` into the workdir; exit code 0 = all held.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from nbdistributed_tpu.manager import topology  # noqa: E402
from nbdistributed_tpu.manager.hostagent import (AgentClient,  # noqa: E402
                                                 _AgentWorker,
                                                 _AgentWorkerIO)
from nbdistributed_tpu.manager.multihost import (HostSpec,  # noqa: E402
                                                 make_launch_plan)
from nbdistributed_tpu.manager.process_manager import (  # noqa: E402
    ProcessManager, wait_until_ready)
from nbdistributed_tpu.messaging import CommunicationManager  # noqa: E402
from nbdistributed_tpu.resilience.supervisor import (  # noqa: E402
    Supervisor, SupervisorPolicy)

NEAR_ADDR = "10.99.0.1"
FAR_ADDR = "10.99.0.2"
AGENT_PORT = 7411
TOKEN = "netns-secret"


def sh(*cmd, check=True) -> int:
    r = subprocess.run(list(cmd), capture_output=True)
    if check and r.returncode != 0:
        raise RuntimeError(f"{' '.join(cmd)}: rc {r.returncode}: "
                           f"{r.stderr.decode(errors='replace')}")
    return r.returncode


def wait_for(cond, timeout, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.2)
    raise RuntimeError(f"timed out waiting for {what}")


def main() -> int:
    workdir = sys.argv[1]
    result = {"ok": False}
    far = None
    comm = pm = sup = client = None
    try:
        sh("ip", "link", "set", "lo", "up")
        far_env = dict(os.environ)
        far_env.pop("NBD_RUN_DIR", None)
        far = subprocess.Popen(
            ["unshare", "-n", sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_netns_far.py"), workdir],
            env=far_env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT)
        pid_file = os.path.join(workdir, "far.pid")
        wait_for(lambda: os.path.exists(pid_file), 30, "far pid")
        far_pid = open(pid_file).read().strip()
        sh("ip", "link", "add", "vethA", "type", "veth", "peer",
           "name", "vethB")
        sh("ip", "link", "set", "vethB", "netns", far_pid)
        sh("ip", "addr", "add", f"{NEAR_ADDR}/24", "dev", "vethA")
        sh("ip", "link", "set", "vethA", "up")
        wait_for(lambda: os.path.exists(
            os.path.join(workdir, "far.ready")), 60, "far agent")

        run_near = os.path.join(workdir, "run_near")
        os.makedirs(run_near, exist_ok=True)
        os.environ["NBD_RUN_DIR"] = run_near

        comm = CommunicationManager(num_workers=2, host=NEAR_ADDR,
                                    auth_token=TOKEN,
                                    session_token="ns-tok",
                                    session_epoch=1)
        # Control-plane-only world (dist_port None): the data plane is
        # not under test here — the control link crossing the veth is.
        plan = make_launch_plan(
            [HostSpec("local"), HostSpec("hostB")],
            coordinator_host=NEAR_ADDR, control_port=comm.port,
            dist_port=None, backend="cpu")
        pm = ProcessManager()
        pm.backend = "cpu"
        pm.world_size = 2
        pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
        ship = {"NBD_AUTH_TOKEN": TOKEN, "NBD_SESSION_TOKEN": "ns-tok",
                "NBD_SESSION_EPOCH": "1", "NBD_ORPHAN_TTL_S": "120"}
        env0 = topology.cpu_worker_env()
        env0.update(dict(plan[0].env))
        env0.update(ship)
        pm._spawn(0, list(plan[0].argv), env0)
        client = AgentClient(FAR_ADDR, AGENT_PORT, auth_token=TOKEN)
        env1 = dict(plan[1].env)
        env1.update(ship)
        pid = client.spawn(1, plan[1].argv, env1)
        pm.processes[1] = _AgentWorker(client, 1, pid)
        pm.io[1] = _AgentWorkerIO(client, 1)
        pm.hosts = {0: "local", 1: "hostB"}
        pm._agents["hostB"] = client
        pm._start_monitor()
        comm.set_host_map(pm.hosts)
        wait_until_ready(comm, pm, 240)

        streamed = []
        comm.set_output_callback(
            lambda r, d: streamed.append((r, d.get("text", ""))))
        resp = comm.send_to_all(
            "execute",
            "print(f'veth-hello-{rank}')\nresult = rank * 10 + 7\n"
            "result", timeout=240)
        assert all(not m.data.get("error") for m in resp.values()), resp
        assert resp[1].data["output"].strip().endswith("17")
        result["streamed_far"] = any(
            r == 1 and "veth-hello-1" in t for r, t in streamed)
        assert result["streamed_far"], (
            "far stdout never crossed the veth", streamed)

        heals = []
        sup = Supervisor(SupervisorPolicy(
            poll_s=0.3, degraded_after_s=3.0, postmortem=False,
            partition_grace_s=120.0),
            heal=lambda: heals.append(1) or None)
        sup.attach(comm, pm)

        # --- a REAL partition: take the link down -------------------
        sh("ip", "link", "set", "vethA", "down")
        wait_for(lambda: "hostB" in sup.status()["suspected_hosts"],
                 40, "partition suspicion")
        result["suspected"] = True
        assert not heals, "healed during a link-down partition"

        # --- and heal it --------------------------------------------
        sh("ip", "link", "set", "vethA", "up")
        wait_for(lambda: not sup.status()["suspected_hosts"], 40,
                 "suspicion to clear after link-up")
        resp = comm.send_to_all("execute", "result2 = rank + 1\n"
                                "result2", timeout=120)
        assert all(not m.data.get("error") for m in resp.values()), resp
        assert not heals
        result["ok"] = True
        return 0
    finally:
        result["heals"] = len(locals().get("heals") or [])
        with open(os.path.join(workdir, "result.json"), "w") as f:
            json.dump(result, f)
        with open(os.path.join(workdir, "stop"), "w") as f:
            f.write("1")
        try:
            if sup is not None:
                sup.stop()
            if pm is not None:
                pm.shutdown()
            if comm is not None:
                comm.shutdown()
        except Exception:
            pass
        if far is not None:
            try:
                far.wait(timeout=10)
            except subprocess.TimeoutExpired:
                far.kill()


if __name__ == "__main__":
    sys.exit(main())
