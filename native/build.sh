#!/bin/sh
# Build the native control-plane transport.
set -e
cd "$(dirname "$0")"
g++ -std=c++17 -O2 -shared -fPIC -pthread \
    -o libnbdtransport.so nbd_transport.cpp
echo "built $(pwd)/libnbdtransport.so"
