// nbd_transport: native control-plane listener for nbdistributed_tpu.
//
// First-party C++ equivalent of the role libzmq (C) plays in the
// reference (reference: pyproject.toml:32 pulls pyzmq; the coordinator
// ROUTER socket lives at communication.py:124-125).  The coordinator's
// fan-in is the control plane's hot point, so it is implemented here as
// an epoll event loop with wire-format framing done in native code; the
// Python layer pops ready events (connect/disconnect/whole frames) from
// a thread-safe queue via ctypes — no Python-callback reentrancy, no
// per-byte GIL traffic.
//
// Protocol (shared with the pure-Python listener in
// nbdistributed_tpu/messaging/transport.py):
//   connection preamble: "NBDW" + int32 rank (little-endian)
//   frames:              "NBD1" + u32 header_len + u64 payload_len + body
//
// Build: native/build.sh  (g++ -O2 -shared -fPIC)

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr char kPreambleMagic[4] = {'N', 'B', 'D', 'W'};
constexpr char kAuthPreambleMagic[4] = {'N', 'B', 'D', 'A'};
constexpr char kFrameMagic[4] = {'N', 'B', 'D', '1'};
constexpr size_t kPreambleSize = 8;
// "NBDA" + i32 rank + sha256(token) digest: the authenticated variant
// required on non-loopback binds (see transport.py — the two
// listeners share one protocol).
constexpr size_t kAuthPreambleSize = 40;
constexpr size_t kDigestSize = 32;
constexpr size_t kFrameHeaderSize = 16;  // magic + u32 hlen + u64 plen
// Per-field sanity bounds, checked BEFORE summing so the total cannot
// overflow (hlen <= 2^30, plen <= 2^40: total < 2^41 << 2^64).  The
// payload bound is far above any real control-plane frame, matching the
// Python listener's effectively-unbounded behavior.
constexpr uint32_t kMaxHeaderLen = 1u << 30;
constexpr uint64_t kMaxPayloadLen = 1ull << 40;

enum EventType : int32_t {
  kEventMessage = 0,
  kEventConnect = 1,
  kEventDisconnect = 2,
};

struct Event {
  int32_t type;
  int32_t rank;
  std::vector<uint8_t> frame;
};

struct Conn {
  int fd = -1;
  int32_t rank = -1;  // -1 until preamble parsed
  std::vector<uint8_t> rbuf;
  std::mutex wlock;

  // The fd is closed only when the last shared_ptr drops: a concurrent
  // Send() holding the Conn keeps the fd number from being reused by a
  // fresh accept while it is mid-write.  Drop paths call ::shutdown
  // first, so such writes fail with EPIPE instead of corrupting a new
  // connection's stream.
  ~Conn() {
    if (fd >= 0) ::close(fd);
  }
};

class Listener {
 public:
  // Must be called before Init (the epoll loop starts inside Init).
  void SetAuthDigest(const uint8_t* digest) {
    std::memcpy(auth_digest_, digest, kDigestSize);
    auth_required_ = true;
  }

  int Init(const char* host, int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return -1;
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) < 0)
      return -1;
    if (::listen(listen_fd_, 128) < 0) return -1;
    socklen_t len = sizeof(addr);
    if (::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
                      &len) < 0)
      return -1;
    bound_port_ = ntohs(addr.sin_port);

    epfd_ = ::epoll_create1(EPOLL_CLOEXEC);
    wake_fd_ = ::eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
    if (epfd_ < 0 || wake_fd_ < 0) return -1;
    AddEpoll(listen_fd_);
    AddEpoll(wake_fd_);
    running_ = true;
    loop_ = std::thread([this] { Loop(); });
    return bound_port_;
  }

  void Close() {
    if (!running_.exchange(false)) return;
    Wake();
    if (loop_.joinable()) loop_.join();
    for (auto& kv : conns_by_fd_) ::shutdown(kv.first, SHUT_RDWR);
    conns_by_fd_.clear();   // destructors close fds once senders finish
    conns_by_rank_.clear();
    if (listen_fd_ >= 0) ::close(listen_fd_);
    if (wake_fd_ >= 0) ::close(wake_fd_);
    if (epfd_ >= 0) ::close(epfd_);
    listen_fd_ = wake_fd_ = epfd_ = -1;
    queue_cv_.notify_all();
  }

  // Blocks up to timeout_ms for the next event.  Returns 1 and fills the
  // out params on success, 0 on timeout, -1 if closed.  The returned
  // frame pointer stays valid until the next Poll call on this handle.
  int Poll(int timeout_ms, int32_t* type, int32_t* rank,
           const uint8_t** data, uint64_t* size) {
    std::unique_lock<std::mutex> lk(queue_mu_);
    if (!queue_cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                            [this] { return !queue_.empty() || !running_; }))
      return 0;
    if (queue_.empty()) return running_ ? 0 : -1;
    current_ = std::move(queue_.front());
    queue_.pop_front();
    *type = current_.type;
    *rank = current_.rank;
    *data = current_.frame.data();
    *size = current_.frame.size();
    return 1;
  }

  // Thread-safe full-frame send to one rank.  0 on success.
  int Send(int32_t rank, const uint8_t* data, uint64_t size) {
    std::shared_ptr<Conn> conn;
    {
      std::lock_guard<std::mutex> g(mu_);
      auto it = conns_by_rank_.find(rank);
      if (it == conns_by_rank_.end()) return -1;
      conn = it->second;
    }
    std::lock_guard<std::mutex> wg(conn->wlock);
    uint64_t sent = 0;
    while (sent < size) {
      ssize_t n = ::send(conn->fd, data + sent, size - sent, MSG_NOSIGNAL);
      if (n < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          // Writer threads may block; the socket is blocking-mode for
          // writes (only reads go through epoll), so this is rare.
          continue;
        }
        return -1;
      }
      sent += static_cast<uint64_t>(n);
    }
    return 0;
  }

  int Ranks(int32_t* out, int max) {
    std::lock_guard<std::mutex> g(mu_);
    int n = 0;
    for (auto& kv : conns_by_rank_) {
      if (n >= max) break;
      out[n++] = kv.first;
    }
    return n;
  }

  int port() const { return bound_port_; }

 private:
  void AddEpoll(int fd) {
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    ::epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
  }

  void Wake() {
    uint64_t one = 1;
    [[maybe_unused]] ssize_t r = ::write(wake_fd_, &one, sizeof(one));
  }

  void Push(Event ev) {
    {
      std::lock_guard<std::mutex> g(queue_mu_);
      queue_.push_back(std::move(ev));
    }
    queue_cv_.notify_one();
  }

  void Loop() {
    epoll_event events[64];
    while (running_.load()) {
      int n = ::epoll_wait(epfd_, events, 64, 500);
      for (int i = 0; i < n; ++i) {
        int fd = events[i].data.fd;
        if (fd == wake_fd_) {
          uint64_t drain;
          while (::read(wake_fd_, &drain, sizeof(drain)) > 0) {
          }
        } else if (fd == listen_fd_) {
          Accept();
        } else {
          Service(fd);
        }
      }
    }
  }

  void Accept() {
    // Level-triggered epoll on a blocking listen socket: one accept per
    // readiness event; remaining backlog re-triggers immediately.
    int fd = ::accept4(listen_fd_, nullptr, nullptr, SOCK_CLOEXEC);
    if (fd < 0) return;
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto conn = std::make_shared<Conn>();
    conn->fd = fd;
    {
      std::lock_guard<std::mutex> g(mu_);
      conns_by_fd_[fd] = conn;
    }
    AddEpoll(fd);
  }

  void Service(int fd) {
    std::shared_ptr<Conn> conn;
    {
      std::lock_guard<std::mutex> g(mu_);
      auto it = conns_by_fd_.find(fd);
      if (it == conns_by_fd_.end()) return;
      conn = it->second;
    }
    uint8_t buf[1 << 16];
    ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
    if (n <= 0) {
      if (n < 0 && (errno == EAGAIN || errno == EINTR)) return;
      Drop(conn);
      return;
    }
    auto& rb = conn->rbuf;
    rb.insert(rb.end(), buf, buf + n);

    if (conn->rank < 0) {
      if (rb.size() < 4) return;
      size_t need;
      bool authed_preamble;
      if (std::memcmp(rb.data(), kAuthPreambleMagic, 4) == 0) {
        need = kAuthPreambleSize;
        authed_preamble = true;
      } else if (std::memcmp(rb.data(), kPreambleMagic, 4) == 0) {
        need = kPreambleSize;
        authed_preamble = false;
      } else {
        Drop(conn);
        return;
      }
      if (rb.size() < need) return;
      if (auth_required_) {
        // Constant-time digest compare: no early-out byte loop.
        uint8_t diff = authed_preamble ? 0 : 1;
        if (authed_preamble) {
          for (size_t i = 0; i < kDigestSize; ++i)
            diff |= static_cast<uint8_t>(rb[8 + i] ^ auth_digest_[i]);
        }
        if (diff != 0) {
          Drop(conn);
          return;
        }
      }
      int32_t rank;
      std::memcpy(&rank, rb.data() + 4, 4);
      rb.erase(rb.begin(), rb.begin() + need);
      conn->rank = rank;
      std::shared_ptr<Conn> old;
      {
        std::lock_guard<std::mutex> g(mu_);
        auto it = conns_by_rank_.find(rank);
        if (it != conns_by_rank_.end()) old = it->second;
        conns_by_rank_[rank] = conn;
      }
      if (old) {
        // Reconnect replaced the rank's connection; silently retire the
        // old socket (no disconnect event — the rank is still live).
        old->rank = -1;
        RemoveFd(old);
      }
      Push({kEventConnect, rank, {}});
    }

    while (true) {
      if (rb.size() < kFrameHeaderSize) break;
      if (std::memcmp(rb.data(), kFrameMagic, 4) != 0) {
        Drop(conn);
        return;
      }
      uint32_t hlen;
      uint64_t plen;
      std::memcpy(&hlen, rb.data() + 4, 4);
      std::memcpy(&plen, rb.data() + 8, 8);
      if (hlen > kMaxHeaderLen || plen > kMaxPayloadLen) {
        Drop(conn);
        return;
      }
      uint64_t total = kFrameHeaderSize + hlen + plen;
      if (rb.size() < total) break;
      Event ev{kEventMessage, conn->rank, {}};
      ev.frame.assign(rb.begin(), rb.begin() + total);
      rb.erase(rb.begin(), rb.begin() + total);
      Push(std::move(ev));
    }
  }

  void RemoveFd(const std::shared_ptr<Conn>& conn) {
    ::epoll_ctl(epfd_, EPOLL_CTL_DEL, conn->fd, nullptr);
    {
      std::lock_guard<std::mutex> g(mu_);
      conns_by_fd_.erase(conn->fd);
    }
    // Half-close now so in-flight Send()s fail fast; the fd itself is
    // closed by ~Conn when the last reference (possibly a sender's)
    // drops — never while another thread could still write to it.
    ::shutdown(conn->fd, SHUT_RDWR);
  }

  void Drop(const std::shared_ptr<Conn>& conn) {
    int32_t rank = conn->rank;
    bool current = false;
    if (rank >= 0) {
      std::lock_guard<std::mutex> g(mu_);
      auto it = conns_by_rank_.find(rank);
      if (it != conns_by_rank_.end() && it->second == conn) {
        conns_by_rank_.erase(it);
        current = true;
      }
    }
    RemoveFd(conn);
    if (current) Push({kEventDisconnect, rank, {}});
  }

  int listen_fd_ = -1, epfd_ = -1, wake_fd_ = -1, bound_port_ = 0;
  uint8_t auth_digest_[kDigestSize] = {};
  bool auth_required_ = false;
  std::atomic<bool> running_{false};
  std::thread loop_;
  std::mutex mu_;  // guards conns_by_fd_ / conns_by_rank_
  std::map<int, std::shared_ptr<Conn>> conns_by_fd_;
  std::map<int32_t, std::shared_ptr<Conn>> conns_by_rank_;

  std::mutex queue_mu_;
  std::condition_variable queue_cv_;
  std::deque<Event> queue_;
  Event current_;
};

}  // namespace

extern "C" {

// Authenticated variant: digest = sha256(token), 32 bytes; null
// digest = no auth required.
void* nbd_listener_create_auth(const char* host, int port,
                               const uint8_t* digest, int* out_port) {
  auto* l = new Listener();
  if (digest) l->SetAuthDigest(digest);
  int p = l->Init(host, port);
  if (p < 0) {
    delete l;
    return nullptr;
  }
  if (out_port) *out_port = p;
  return l;
}

void* nbd_listener_create(const char* host, int port, int* out_port) {
  return nbd_listener_create_auth(host, port, nullptr, out_port);
}

int nbd_listener_poll(void* h, int timeout_ms, int32_t* type, int32_t* rank,
                      const uint8_t** data, uint64_t* size) {
  return static_cast<Listener*>(h)->Poll(timeout_ms, type, rank, data, size);
}

int nbd_listener_send(void* h, int32_t rank, const uint8_t* data,
                      uint64_t size) {
  return static_cast<Listener*>(h)->Send(rank, data, size);
}

int nbd_listener_ranks(void* h, int32_t* out, int max) {
  return static_cast<Listener*>(h)->Ranks(out, max);
}

void nbd_listener_close(void* h) {
  auto* l = static_cast<Listener*>(h);
  l->Close();
  delete l;
}

}  // extern "C"
