"""Benchmark: the framework's headline numbers, measured through the
real stack (worker processes driven cell-by-cell over the control
plane), resilient to accelerator-tunnel flaps.

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "extra": {...}}

Three measurements per run (BASELINE.json configs #3 and #5 + the
driver-defined all_reduce metric):

1. **Cell-wise DDP step/s** (primary metric): an SGD loop on
   Linear(1024,1024), each step its own ``execute`` cell — compute plus
   the full interactive-framework overhead.  ``vs_baseline`` compares
   against the reference's architectural per-cell floor (~0.2 s: its
   coordinator polls the ZMQ socket and the display buffer at 100 ms
   each, SURVEY §3.2) on top of the same measured compute.
2. **Flagship-model MFU** (``extra.smol135m``): SmolLM2-135M-scale
   config, bf16, flash kernels — forward and train-step tokens/s on
   rank 0's accelerator, converted to model FLOP/s against the chip
   peak (v5e: 197 bf16 TFLOP/s) with analytic matmul FLOPs/token.
3. **all_reduce bandwidth sweep** (``extra.allreduce``): bus bandwidth
   2(n-1)/n·bytes/t per chip at 1–64 MiB.  On a single-chip world the
   collective degenerates, so the sweep reports the HBM-bound on-device
   copy figure instead, labeled as such.
4. **Elastic pools** (``extra.elastic``, ISSUE 16): cold vs warm
   first-cell compile seconds (the persistent XLA cache serving a
   resized-in fleet), the resize drain-barrier + whole-flip
   wall-clock, and a tenant migration end to end — measured in CPU
   pools of their own after the bench world is torn down.
5. **Serving fast path** (``extra.serving``, ISSUE 17): closed-loop
   loadgen against a paged, multi-rank decode plane — sustained
   tokens/s with client-observed p99 TTFT/TPOT, then the shed rate
   at 2x the measured sustainable rate — in a CPU pool of its own.
6. **Training integrity guard** (``extra.trainguard``, ISSUE 19):
   guarded vs unguarded DDP steps/s at the default audit/snapshot
   cadences plus the audit step's fingerprint cost — the <10%
   guarded-overhead acceptance number, measured on CPU in-process.

TPU bring-up failures (the axon tunnel flaps: device discovery hangs)
retry with backoff, then fall back to a 2-process CPU/gloo world — the
metric name always carries the backend that actually ran.

**Per-measurement process isolation is the rule**: every heavy TPU
measurement family (MFU, flash-vs-XLA, decode, speculative, serving,
7B int8) runs in its own freshly-spawned worker process, torn down
(blocking) before the next spawns — see :func:`measure_family`.
"""

from __future__ import annotations

import ast
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from nbdistributed_tpu.manager import ProcessManager, topology
from nbdistributed_tpu.messaging import CommunicationManager
from nbdistributed_tpu.utils import knobs

STEPS = 60
WARMUP = 5
TPU_ATTEMPTS = (0, 30)  # seconds of backoff before each try
V5E_PEAK_BF16 = 197e12

SETUP = """
import jax, jax.numpy as jnp, optax
key = jax.random.PRNGKey(rank)
W = jax.random.normal(key, (1024, 1024), jnp.float32) * 0.02
b = jnp.zeros((1024,), jnp.float32)
opt = optax.sgd(1e-3)
state = opt.init((W, b))
x = jax.random.normal(jax.random.PRNGKey(100 + rank), (256, 1024))
y = jax.random.normal(jax.random.PRNGKey(200 + rank), (256, 1024))

def loss_fn(params, x, y):
    W, b = params
    pred = x @ W + b
    return jnp.mean((pred - y) ** 2)

if world_size > 1:
    # DDP: jit the two halves and all-reduce grads eagerly in between
    # (eager collectives cannot be traced into jit).
    @jax.jit
    def local_grads(params, x, y):
        return jax.value_and_grad(loss_fn)(params, x, y)

    @jax.jit
    def apply_grads(params, state, g):
        u, state = opt.update(g, state, params)
        return optax.apply_updates(params, u), state

    def local_step(params, state, x, y):
        l, g = local_grads(params, x, y)
        g = jax.tree.map(lambda t: all_reduce(t, "mean"), g)
        params, state = apply_grads(params, state, g)
        return params, state, l
else:
    # Single worker: one fused XLA program, no collective needed.
    @jax.jit
    def local_step(params, state, x, y):
        l, g = jax.value_and_grad(loss_fn)(params, x, y)
        u, state = opt.update(g, state, params)
        return optax.apply_updates(params, u), state, l

params = (W, b)
params, state, _ = local_step(params, state, x, y)  # compile
jax.block_until_ready(params)
'ready'
"""

STEP_CELL = """
params, state, loss_val = local_step(params, state, x, y)
jax.block_until_ready(params)
float(loss_val)
"""

# Flagship-model MFU, measured on the worker's accelerator.  The final
# expression is a json.dumps string so the coordinator can parse the
# result out of the REPL echo.
MFU_CELL = """
import functools as _functools, json as _json, time as _time
import jax as _jax, jax.numpy as _jnp, optax as _optax
from nbdistributed_tpu.models import (forward as _fwd_fn,
                                      init_params as _init,
                                      loss_fn as _loss,
                                      {cfg_name} as _cfg_fn)

_cfg = _cfg_fn(dtype=_jnp.bfloat16, use_flash=True{extra_cfg})
# Train step uses per-layer remat — the standard long-context training
# configuration (keeps activation memory O(S); without it the B=8
# S=2048 train step needs ~20 G HBM vs the v5e's 16 G).  MFU stays the
# PaLM convention: 3x fwd model FLOPs, recompute not counted.
_cfg_t = _cfg_fn(dtype=_jnp.bfloat16, use_flash=True,
                 remat=True{extra_cfg})
_p = _init(_jax.random.PRNGKey(0), _cfg)
_B, _S, _N = {shape}
# Timed-loop repetitions (fwd, train): median/min across reps guards
# against the tunnel's one-off spikes.  The CPU fallback passes (1, 1)
# — host timing has no spikes and the fallback must stay quick.
_R_FWD, _R_TR = {reps}
# Token buffer at 4x the fwd batch: the train ladder probes UPWARD
# from 2*_B (per-layer remat keeps activations O(S) per layer, so a
# bigger batch often fits and lifts MFU) and the chunked-CE control
# row probes 2x beyond whatever that finds; _tok[:_vB] then slices a
# genuine _vB rows instead of silently capping.
_tok = _jax.random.randint(_jax.random.PRNGKey(1), (4 * _B, _S), 0,
                           _cfg.vocab_size)

# Analytic matmul FLOPs/token (fwd): qkv + out projections, SwiGLU
# mlp, the two attention einsums at causal-average S/2 keys, lm_head.
_d, _L, _H, _Hkv, _Dh, _ff, _V = (_cfg.d_model, _cfg.n_layers,
                                  _cfg.n_heads, _cfg.n_kv_heads,
                                  _cfg.head_dim, _cfg.d_ff,
                                  _cfg.vocab_size)
_per_layer = (2 * _d * _H * _Dh + 2 * _d * 2 * _Hkv * _Dh
              + 2 * _H * _Dh * _d + 3 * 2 * _d * _ff)
_attn = 2 * 2 * (_S / 2) * _H * _Dh
_fwd_flops_tok = _L * (_per_layer + _attn) + 2 * _d * _V

# The fwd loop donates the previous logits buffer: the timed loop
# stays fully async (blocking each iteration would add a full tunnel
# round-trip ~70 ms/step) yet only ONE B*S*V logits buffer ever
# exists (~1 G at 1B scale — an undonated async loop queues _N of
# them in flight and OOMs the 16 G chip).  keep_unused=True is
# load-bearing: without it JAX prunes the unused arg and silently
# drops the donation (no aliasing, no eager free).
# Every iteration runs on DIFFERENT token values and the loop ends in
# a value fetch: identical repeated inputs are served by the tunnel's
# program+input result cache and block_until_ready is async-acked, so
# the naive fixed-input loop "measures" a free forward.  Median of 3
# timed loops tames the window's second-scale one-off spikes.
_f = _jax.jit(lambda p, t, prev: _fwd_fn(p, t, _cfg),
              donate_argnums=(2,), keep_unused=True)
_ftok = _tok[:_B]
_prev = _jnp.zeros((_B, _S, _cfg.vocab_size), _jnp.float32)
_t0 = _time.time(); _o = _f(_p, _ftok, _prev)
float(_o[0, 0, 0])
_fwd_compile_s = _time.time() - _t0
_fwd_samples = []
for _rep in range(_R_FWD):
    _t0 = _time.time()
    for _i in range(_N):
        _ti = (_ftok + (_rep * _N + _i + 1)) % _cfg.vocab_size
        _o = _f(_p, _ti, _o)
    float(_o[0, 0, 0])            # value fetch forces the whole loop
    _fwd_samples.append((_time.time() - _t0) / _N)
_fwd_s = sorted(_fwd_samples)[len(_fwd_samples) // 2]
_o = None   # 1 G of logits must not stay live across the train phase

_opt = _optax.adamw(1e-4)

# Donate params + opt state so XLA updates them in place: without
# donation the step holds both generations of (params, mu, nu) —
# 2x 6.6 G at 1B scale — which is exactly what OOMed the first
# on-chip run of this cell.
@_jax.jit
def _mk_state(p):
    return _opt.init(p)

# Train-phase batch ladder: start at the caller-chosen batch (the
# TPU families probe 2*_B first, the CPU fallback _B), halve on
# ResourceExhausted (the train step needs ~2.5x the fwd working set).
def _time_train(_cfg_variant, _start_B):
    _tr = _comp = None
    _vB = _start_B
    _loss2 = lambda p, t: _loss(p, {{"tokens": t}}, _cfg_variant)
    while _vB >= 1:
        try:
            @_functools.partial(_jax.jit, donate_argnums=(0, 1))
            def _train(p, s, t):
                l, g = _jax.value_and_grad(_loss2)(p, t)
                u, s = _opt.update(g, s, p)
                return _optax.apply_updates(p, u), s, l

            _ttok = _tok[:_vB]
            _st = _mk_state(_p)
            _t0 = _time.time()
            _p2, _st2, _l = _train(_jax.tree_util.tree_map(
                _jnp.copy, _p), _st, _ttok)
            float(_l)                 # value fetch, not an async ack
            _comp = _time.time() - _t0
            # Params/opt state evolve every step, so the loop is
            # cache-proof by construction; two timed loops (min) guard
            # against the tunnel's one-off second-scale spikes.
            _trs = []
            for _rep in range(_R_TR):
                _t0 = _time.time()
                for _ in range(_N):
                    _p2, _st2, _l = _train(_p2, _st2, _ttok)
                float(_l)
                _trs.append((_time.time() - _t0) / _N)
            _tr = min(_trs)
            _p2 = _st2 = _st = None
            return _tr, _comp, _vB
        except Exception as _e:
            if "RESOURCE_EXHAUSTED" not in str(_e):
                raise
            _p2 = _st2 = _st = _train = None
            import gc as _gc; _gc.collect()
            _vB //= 2
    return None, None, 0


# Ladder start ({tr_start}): on TPU it probes UPWARD from 2*_B —
# per-layer remat keeps activation memory O(S) per layer, so a bigger
# batch than the fwd pass often fits, and more tokens per step is the
# cheapest MFU lever there is.  OOM halves back (one extra compile,
# amortized by the persistent compilation cache).  The CPU fallback
# passes _B to stay inside its budget.
_tr_s, _train_compile_s, _train_B = _time_train(_cfg_t, {tr_start})
if _tr_s is None:
    raise RuntimeError("train step OOMed even at batch 1")
# The remat-policy table (VERDICT r3 item 3): full remat recomputes
# the whole forward; "dots" keeps matmul outputs (min recompute, max
# memory); "attn_only"/"mlp_only" checkpoint one sub-block.  Measure
# every policy that fits so the round records WHICH one wins at this
# scale/HBM, not just that a knob exists.
import dataclasses as _dc

def _row(_tp, _tb):
    return (None if _tp is None else
            {{"ms": round(_tp * 1e3, 2), "batch": _tb,
              "mfu": round(_tb * _S / _tp * 3 * _fwd_flops_tok
                           / {peak}, 4)}})

_policies = {{}}
for _pol in ("dots", "attn_only", "mlp_only"):
    _tp, _, _tb = _time_train(
        _dc.replace(_cfg_t, remat_policy=_pol), _train_B)
    _policies[_pol] = _row(_tp, _tb)
# Control row, NOT a remat policy: use_flash=False swaps the Pallas
# flash fwd+bwd kernels for the reference einsum attention compiled
# by XLA (materializes the (B, H, S, S) scores — the same baseline
# the flash speedup row compares against), in the SAME remat config.
# If this row beats the flash rows, the Pallas backward is costing
# more than it saves and the honest train setting is XLA attention.
# Ladder starts at _B, not _train_B: the materialized scores OOM far
# earlier than flash-remat, and every OOM rung costs a cold compile.
_tp, _, _tb = _time_train(_dc.replace(_cfg_t, use_flash=False), _B)
_ref_attn_row = _row(_tp, _tb)
# Chunked-vocab CE control row (ops/xent.py): the (B, S, V) logits
# never materialize — the buffer that caps the train batch — so the
# ladder probes 2x beyond whatever batch the standard loss found.
_tp, _, _tb = _time_train(
    _dc.replace(_cfg_t, ce_chunk=_cfg.vocab_size // 4),
    2 * max(_train_B, _B))
_ce_chunk_row = _row(_tp, _tb)
_tr_d = None if _policies["dots"] is None else \
    _policies["dots"]["ms"] / 1e3
_train_B_d = 0 if _policies["dots"] is None else \
    _policies["dots"]["batch"]

_peak = {peak}
_json.dumps({{
    "batch": _B, "seq": _S, "train_batch": _train_B,
    "n_params_m": round(sum(x.size for x in
                            _jax.tree_util.tree_leaves(_p)) / 1e6, 1),
    "fwd_ms": round(_fwd_s * 1e3, 2),
    "fwd_tokens_per_s": round(_B * _S / _fwd_s),
    "fwd_tflops_per_s": round(_B * _S / _fwd_s * _fwd_flops_tok / 1e12,
                              2),
    "fwd_mfu": round(_B * _S / _fwd_s * _fwd_flops_tok / _peak, 4),
    "train_ms": round(_tr_s * 1e3, 2),
    "train_tokens_per_s": round(_train_B * _S / _tr_s),
    "train_tflops_per_s": round(_train_B * _S / _tr_s
                                * 3 * _fwd_flops_tok / 1e12, 2),
    "train_mfu": round(_train_B * _S / _tr_s * 3 * _fwd_flops_tok
                       / _peak, 4),
    "train_dots_ms": (None if _tr_d is None else round(_tr_d * 1e3, 2)),
    "train_dots_mfu": (None if _tr_d is None else
                       round(_train_B_d * _S / _tr_d
                             * 3 * _fwd_flops_tok / _peak, 4)),
    "train_dots_batch": _train_B_d,
    "train_remat_policies": _policies,
    "train_ref_attn": _ref_attn_row,
    "train_ce_chunk": _ce_chunk_row,
    "compile_s": [round(_fwd_compile_s, 1), round(_train_compile_s, 1)],
}})
"""

# Flash kernel vs XLA reference attention.  Timing is CHAINED: each
# iteration's q depends on the previous output, all inside one scan
# program, and per-call time is the (long - short) chain difference —
# the only pattern that survives the axon tunnel's async-ack/caching
# behavior (a plain dispatch loop + block_until_ready measured 0.03 ms
# for a 35-GFLOP attention, 5x past the chip's peak).  Each chain
# length is the MEDIAN of several fresh-input timed calls: the
# 2026-08-01 window showed second-scale one-off spikes on single
# timed samples (t18-t2 deltas came out negative or 50x high), so a
# single-shot delta is noise — the median of 3+ is stable.
FLASH_CELL = """
import json as _json
import jax as _jax, jax.numpy as _jnp
from nbdistributed_tpu.ops import attention_reference as _ref
from nbdistributed_tpu.ops import flash_attention as _flash
from nbdistributed_tpu.ops.timing import chained_delta_ms as _cdm
_B, _S, _H, _Hkv, _D = 4, 2048, 8, 2, 128
_q = _jax.random.normal(_jax.random.PRNGKey(0), (_B, _S, _H, _D),
                        _jnp.bfloat16)
_k = _jax.random.normal(_jax.random.PRNGKey(1), (_B, _S, _Hkv, _D),
                        _jnp.bfloat16)
_v = _jax.random.normal(_jax.random.PRNGKey(2), (_B, _S, _Hkv, _D),
                        _jnp.bfloat16)

_out = {}
_fm, _fsamp = _cdm(lambda q: _flash(q, _k, _v, True), _q)
_rm, _rsamp = _cdm(lambda q: _ref(q, _k, _v, causal=True), _q)
_out["flash_ms"] = None if _fm <= 0 else round(_fm, 3)
_out["xla_ref_ms"] = None if _rm <= 0 else round(_rm, 3)
_out["speedup"] = (None if _fm <= 0 or _rm <= 0
                   else round(_rm / _fm, 3))
_out["samples"] = {"flash": _fsamp, "xla_ref": _rsamp}
_out["shape"] = (f"B{_B} S{_S} H{_H} Hkv{_Hkv} D{_D} "
                 f"{_q.dtype.name} causal, chained median-of-5 timing")
_json.dumps(_out)
"""

# Single-batch decode throughput, fp vs int8 weight-only: decode is
# HBM-bound (every step streams every weight), so int8 should approach
# 2x.  Per-token time is the DELTA between a long and a short generate
# program (median of fresh-prompt reps each): the delta cancels the
# fixed dispatch+fetch round-trip, every timed call uses a prompt no
# earlier call saw (a program+input result cache can never serve it),
# and the final np.asarray is a value fetch (block_until_ready is
# async-acked by the tunnel and proves nothing — the 2026-08-01 window
# "measured" a 64-step weight-streaming decode at 0.096 ms that way).
# Each row also reports tokens/s as a percent of the v5e HBM roofline
# (VERDICT r4 #2): bytes/token = weight bytes + the FULL allocated KV
# cache (the decode kernel's grid covers every k-block of max_len and
# masks in compute — static shapes stream it all), and the roofline is
# 819 GB/s / bytes_per_token.
DECODE_CELL = """
import json as _json, time as _time
import jax as _jax, jax.numpy as _jnp, numpy as _np
from nbdistributed_tpu.models import (init_params as _init,
                                      make_generate_fn as _mkgen,
                                      quantize_params as _quant,
                                      quantize_params4 as _quant4,
                                      smol_135m_config as _cfg_fn)
_cfg = _cfg_fn(dtype=_jnp.bfloat16, use_flash=True)
_p = _init(_jax.random.PRNGKey(0), _cfg)
_qp = _quant(_p)
_q4p = _quant4(_p)
_N1, _N2, _ML = 32, 256, 512
_HBM_V5E = 819e9
_REPS = 3

def _tree_bytes(t):
    return sum(x.size * x.dtype.itemsize
               for x in _jax.tree_util.tree_leaves(t))

def _kv_bytes(q8):
    _per_tok = _cfg.n_layers * _cfg.n_kv_heads * _cfg.head_dim
    _kv = 2 * _per_tok * _ML * (1 if q8 else 2)
    if q8:
        _kv += 2 * _cfg.n_layers * _cfg.n_kv_heads * _ML * 4  # scales
    return _kv

def _prompt_for(_seed):
    return _jax.random.randint(_jax.random.PRNGKey(_seed), (1, 16), 0,
                               _cfg.vocab_size)

_seed = [0]
def _median_gen_s(_g, _params):
    _ts = []
    for _ in range(_REPS):
        _seed[0] += 1
        _pr = _prompt_for(_seed[0])
        _t0 = _time.time()
        int(_np.asarray(_g(_params, _pr))[0, -1])   # value fetch
        _ts.append(_time.time() - _t0)
    _ts.sort()
    return _ts[len(_ts) // 2]

_out = {}
for _name, _params, _q8 in (("bf16", _p, False),
                            ("int8", _qp, False),
                            ("int8_kv8", _qp, True),
                            ("int4_kv8", _q4p, True)):
    _g1 = _mkgen(_cfg, _N1, max_len=_ML, kv_quantized=_q8)
    _g2 = _mkgen(_cfg, _N2, max_len=_ML, kv_quantized=_q8)
    _seed[0] += 1
    int(_np.asarray(_g1(_params, _prompt_for(_seed[0])))[0, -1])
    _seed[0] += 1
    int(_np.asarray(_g2(_params, _prompt_for(_seed[0])))[0, -1])
    _lo = _median_gen_s(_g1, _params)
    _hi = _median_gen_s(_g2, _params)
    _per_tok_s = (_hi - _lo) / (_N2 - _N1)
    _bpt = _tree_bytes(_params) + _kv_bytes(_q8)
    if _per_tok_s <= 0:
        _out[_name + "_tok_per_s"] = None     # noise won: say so
        _out[_name + "_ms_per_tok"] = None
        _out[_name + "_roofline_pct_v5e"] = None
    else:
        _tps = 1.0 / _per_tok_s
        _out[_name + "_tok_per_s"] = round(_tps, 1)
        _out[_name + "_ms_per_tok"] = round(_per_tok_s * 1e3, 3)
        _out[_name + "_roofline_pct_v5e"] = round(
            100.0 * _tps / (_HBM_V5E / _bpt), 1)
    _out[_name + "_bytes_per_tok_mb"] = round(_bpt / 1e6, 1)
    _out[_name + "_lo_hi_s"] = [round(_lo, 4), round(_hi, 4)]
_out["int8_speedup"] = (
    round(_out["int8_tok_per_s"] / _out["bf16_tok_per_s"], 2)
    if _out["bf16_tok_per_s"] and _out["int8_tok_per_s"] else None)
_json.dumps(_out)
"""

# Speculative decoding with a self-draft: acceptance is always gamma
# (upper bound), so the row isolates the MECHANICS — how much of the
# per-token cost the batched verify amortizes when acceptance is high.
# A real small draft lands between this and plain decode.
SPEC_CELL = """
import json as _json, time as _time
import jax as _jax, jax.numpy as _jnp, numpy as _np
from nbdistributed_tpu.models import (generate as _gen,
                                      init_params as _init,
                                      quantize_params4 as _quant4,
                                      smol_135m_config as _cfg_fn,
                                      speculative_generate as _spec)
_cfg = _cfg_fn(dtype=_jnp.bfloat16, use_flash=True)
_p = _init(_jax.random.PRNGKey(0), _cfg)
_q4 = _quant4(_p)
_N1, _N2, _G, _B = 16, 64, 4, 4
_REPS = 3

def _mk(_n, _mode):
    # "spec" = self-draft (acceptance == gamma, pure-mechanics upper
    # bound); "spec4" = int4-quantized-self draft (the textbook cheap
    # draft: near-gamma acceptance, draft forward streams half the
    # bytes) — the realistic point between self-draft and plain.
    if _mode == "spec":
        return _jax.jit(lambda p, t: _spec(p, p, t, _cfg, _cfg, _n,
                                           gamma=_G))
    if _mode == "spec4":
        # Draft tree rides as a traced ARGUMENT, not a closure: a
        # closed-over pytree is baked into each executable as
        # constants (extra HBM copies, slower compiles).
        _f4 = _jax.jit(lambda p, d, t: _spec(p, d, t, _cfg, _cfg, _n,
                                             gamma=_G))
        return lambda p, t: _f4(p, _q4, t)
    return _jax.jit(lambda p, t: _gen(p, t, _cfg, _n))

_seed = [100]
def _prompt_for(_b):
    _seed[0] += 1
    return _jax.random.randint(_jax.random.PRNGKey(_seed[0]), (_b, 16),
                               0, _cfg.vocab_size)

def _fetch(_r):
    # Value fetch forces completion (block_until_ready is async-acked
    # over the tunnel); fresh prompts per rep defeat result caches.
    _toks = _r[0] if isinstance(_r, tuple) else _r
    int(_np.asarray(_toks)[0, -1])
    return _r

def _median_s(_f, _b):
    _ts = []
    for _ in range(_REPS):
        _pr = _prompt_for(_b)
        _t0 = _time.time()
        _r = _fetch(_f(_p, _pr))
        _ts.append(_time.time() - _t0)
    _ts.sort()
    return _ts[len(_ts) // 2], _r

_out = {}
_spec_r = None
# Batched streams share every draft/verify forward, so B streams cost
# ~one stream's wall-clock: report aggregate tokens/s at B=1 and B=4.
# Per-token time = (N2-run - N1-run)/(N2-N1), medians of fresh-prompt
# reps — the delta cancels the fixed dispatch+fetch round-trip.
for _name, _mode, _b in (("plain", "plain", 1),
                         ("spec_selfdraft", "spec", 1),
                         ("plain_b4", "plain", _B),
                         ("spec_selfdraft_b4", "spec", _B),
                         ("spec_int4draft_b4", "spec4", _B)):
    _f1, _f2 = _mk(_N1, _mode), _mk(_N2, _mode)
    _fetch(_f1(_p, _prompt_for(_b)))     # compile + first run
    _fetch(_f2(_p, _prompt_for(_b)))
    _lo, _ = _median_s(_f1, _b)
    _hi, _r = _median_s(_f2, _b)
    _per_tok = (_hi - _lo) / (_N2 - _N1)
    _out[_name + "_tok_per_s"] = (
        None if _per_tok <= 0 else round(_b / _per_tok, 1))
    _out[_name + "_lo_hi_s"] = [round(_lo, 4), round(_hi, 4)]
    if _mode == "spec4":
        _out["int4draft_mean_accepted"] = round(float(_r[1]), 2)
    elif _mode == "spec":
        _spec_r = _r
_out["gamma"] = _G
_out["batch"] = _B
_out["mean_accepted"] = round(float(_spec_r[1]), 2)
_json.dumps(_out)
"""

# Continuous-batching server vs sequential decode.  Decode is
# HBM-bound (every step streams the weights once regardless of B), so
# B requests served together approach Bx the aggregate tokens/s of
# serving them one after another.  Three rows:
#   sequential  — B separate generate() calls (the no-server baseline)
#   batched_gen — one generate() at batch B (device-side upper bound)
#   server      — DecodeServer, which adds the per-step host sync the
#                 interactive streaming/EOS contract requires (over
#                 the axon tunnel that round-trip is the dominant
#                 per-step cost — reported as-is, it IS the product).
SERVE_CELL = """
import json as _json, time as _time
import jax as _jax, jax.numpy as _jnp, numpy as _np
from nbdistributed_tpu.models import (DecodeServer, init_params,
                                      make_generate_fn,
                                      smol_135m_config)
_cfg = smol_135m_config(dtype=_jnp.bfloat16, use_flash=True)
_p = init_params(_jax.random.PRNGKey(0), _cfg)
_N, _B, _L = 48, 4, 16
_prompts = [[(7 * i + j) % 100 + 1 for j in range(_L)]
            for i in range(_B)]
_g1 = make_generate_fn(_cfg, _N, max_len=256)
_gB = make_generate_fn(_cfg, _N, max_len=256)
_pb = _jnp.asarray(_prompts, _jnp.int32)

# Warm with prompt VALUES the timed calls never reuse, end every
# timed call in a value fetch (block_until_ready is async-acked over
# the tunnel), and take the median of 3 varied-input reps — a
# program+input result cache can never serve a timed call.
_warm = (_pb + 37) % _cfg.vocab_size
int(_np.asarray(_g1(_p, _warm[:1]))[0, -1])     # warm B=1
int(_np.asarray(_gB(_p, _warm))[0, -1])         # warm B=4

def _median3(_f):
    _ts = []
    for _rep in range(3):
        _pbr = (_pb + _rep * 101) % _cfg.vocab_size
        _t0 = _time.time()
        _f(_pbr)
        _ts.append(_time.time() - _t0)
    _ts.sort()
    return _ts[1]

def _run_seq(_pbr):
    for _i in range(_B):
        int(_np.asarray(_g1(_p, _pbr[_i:_i + 1]))[0, -1])

_dt_seq = _median3(_run_seq)
_dt_bat = _median3(lambda _pbr: int(_np.asarray(_gB(_p, _pbr))[0, -1]))

_srv = DecodeServer(_p, _cfg, max_batch=_B, max_len=256, pad_to=_L)
_w = _srv.submit(_prompts[0], 2)                # warm prefill + step
_srv.run_until_done(); _srv.release(_w)
_t0 = _time.time()
_rids = [_srv.submit(_pr, _N) for _pr in _prompts]
_srv.run_until_done(max_steps=4 * _N)
_dt_srv = _time.time() - _t0
assert all(len(_srv.outputs[_r]) == _N for _r in _rids)

# step_many(8): 8 decode steps per host sync — the amortization for
# high-latency links (the tunnel's ~70 ms round-trip otherwise
# dominates per-token time).
_srv2 = DecodeServer(_p, _cfg, max_batch=_B, max_len=256, pad_to=_L)
_w = _srv2.submit(_prompts[0], 10)      # warm prefill AND the 8-step
while not _srv2.done():                 # scan program pre-_t0
    _srv2.step_many(8)
_srv2.release(_w)
_t0 = _time.time()
_rids2 = [_srv2.submit(_pr, _N) for _pr in _prompts]
while not _srv2.done():
    _srv2.step_many(8)
_dt_many = _time.time() - _t0
assert all(len(_srv2.outputs[_r]) == _N for _r in _rids2)

# Speculative server with spec_step_many(2): up to 2*(gamma+1) tokens
# per host sync — the compounded amortization (self-draft = the
# gamma-acceptance upper bound, as in the SPEC row).
_srv3 = DecodeServer(_p, _cfg, max_batch=_B, max_len=256, pad_to=_L,
                     draft_params=_p, draft_cfg=_cfg, gamma=4)
_w = _srv3.submit(_prompts[0], 10)      # warm prefills + the scan
while not _srv3.done():
    _srv3.spec_step_many(2)
_srv3.release(_w)
_t0 = _time.time()
_rids3 = [_srv3.submit(_pr, _N) for _pr in _prompts]
while not _srv3.done():
    _srv3.spec_step_many(2)
_dt_spec_many = _time.time() - _t0
assert all(len(_srv3.outputs[_r]) == _N for _r in _rids3)

# Prefix-cache admission cost (VERDICT r4 #4): _B requests sharing a
# 128-token system prefix + 8-token suffixes.  Admission with
# cache_prefix = one HBM copy + an 8-token suffix prefill vs a full
# 136-token prefill — time ONLY the submit() loop (admission runs
# prefill eagerly; no decode steps intrude).
_PL, _SL = 128, 8
_pfx = [(13 * _j) % 100 + 1 for _j in range(_PL)]
_sfx = [[(7 * _i + _j) % 100 + 1 for _j in range(_SL)]
        for _i in range(_B)]
# Warm with a suffix the timed loop never submits (same prompt values
# after an identical release would hand a result cache a free hit).
_wsfx = [(11 * _j) % 100 + 101 for _j in range(_SL)]
_srv4 = DecodeServer(_p, _cfg, max_batch=_B, max_len=256, pad_to=8)
_w = _srv4.submit(_pfx + _wsfx, 1)              # warm both buckets
_srv4.run_until_done(); _srv4.release(_w)
_t0 = _time.time()
for _s in _sfx:
    _srv4.submit(_pfx + _s, 1)
_srv4.run_until_done()
_dt_admit_plain = _time.time() - _t0
_srv5 = DecodeServer(_p, _cfg, max_batch=_B, max_len=256, pad_to=8)
_srv5.cache_prefix(_pfx)
_w = _srv5.submit(_pfx + _wsfx, 1)              # warm absorb + suffix
_srv5.run_until_done(); _srv5.release(_w)
_t0 = _time.time()
for _s in _sfx:
    _srv5.submit(_pfx + _s, 1)
_srv5.run_until_done()
_dt_admit_pfx = _time.time() - _t0
assert all(_srv4.outputs[_r] == _srv5.outputs[_r]
           for _r in _srv4.outputs if _r in _srv5.outputs)

_tot = _B * _N
_json.dumps({
    "batch": _B, "new_tokens": _N,
    "sequential_tok_per_s": round(_tot / _dt_seq, 1),
    "batched_generate_tok_per_s": round(_tot / _dt_bat, 1),
    "server_tok_per_s": round(_tot / _dt_srv, 1),
    "server_stepmany8_tok_per_s": round(_tot / _dt_many, 1),
    "server_spec_many2_tok_per_s": round(_tot / _dt_spec_many, 1),
    "batching_speedup": round(_dt_seq / _dt_bat, 2),
    "server_vs_sequential": round(_dt_seq / _dt_srv, 2),
    "per_step_host_sync_ms": round(
        (_dt_srv - _dt_bat) / _N * 1e3, 2),
    "admit_prefix_len": _PL,
    "admit_ms_plain": round(_dt_admit_plain / _B * 1e3, 1),
    "admit_ms_prefix_cached": round(_dt_admit_pfx / _B * 1e3, 1),
    "admit_prefix_speedup": round(_dt_admit_plain / _dt_admit_pfx, 2),
})
"""


# 7B-class quantized decode at a real memory footprint (BASELINE.json
# config #5's Llama-2-7B intent): weights init on the host CPU backend
# (a full bf16 7B never touches the 16G chip) and are quantized there;
# the int8 (~6.7G) and int4 (~3.4G) trees move to the TPU one at a
# time (two generate programs compile per variant).  Decode is
# weight-streaming-bound, so tokens/s tracks HBM bandwidth and int4
# should approach 2x int8.
DECODE7B_CELL = """
import gc as _gc, json as _json, time as _time
import jax as _jax, jax.numpy as _jnp
from nbdistributed_tpu.models import (init_params as _init,
                                      llama2_7b_config as _cfg_fn,
                                      make_generate_fn as _mkgen,
                                      quantize_params as _quant,
                                      quantize_params4 as _quant4)
_cfg = _cfg_fn(dtype=_jnp.bfloat16, use_flash=True)
# Host-side init via numpy, not jax.random: threefry for 6.7e9
# elements on the CPU backend takes 20+ minutes; numpy's generator
# fills the same tree in ~1 min.  Values only need realistic scale —
# decode timing on TPU is value-independent.
import numpy as _np
_shapes = _jax.eval_shape(lambda k: _init(k, _cfg),
                          _jax.random.PRNGKey(0))
_rng = _np.random.default_rng(0)
with _jax.default_device(_jax.devices("cpu")[0]):
    _p_host = _jax.tree_util.tree_map(
        lambda s: _jnp.asarray(
            (_rng.standard_normal(s.shape, _np.float32) * 0.02),
            s.dtype),
        _shapes)
_dev = _jax.devices()[0]
_N1, _N2, _CL = 8, 32, 2048
# Roofline %: the decode kernel streams the FULL allocated cache every
# step (static grid over max_len k-blocks, masked compute), so
# bytes/token = weights + int8 K+V rows + fp32 scales at _CL.
_kv_bytes = (2 * _cfg.n_layers * _cfg.n_kv_heads * _CL
             * (_cfg.head_dim * 1 + 4))

_seed = [0]
def _prompt_for():
    _seed[0] += 1
    return _jax.random.randint(_jax.random.PRNGKey(_seed[0]), (1, 16),
                               0, _cfg.vocab_size)

# Per-token time = delta between a long and a short generate program
# (medians of fresh-prompt reps): cancels the fixed round-trip, and
# the np.asarray value fetch forces completion (block_until_ready is
# async-acked over the tunnel; same-input repeats hit result caches).
def _median_s(_g, _qp, _reps=3):
    _ts = []
    for _ in range(_reps):
        _pr = _prompt_for()
        _t0 = _time.time()
        int(_np.asarray(_g(_qp, _pr))[0, -1])
        _ts.append(_time.time() - _t0)
    _ts.sort()
    return _ts[len(_ts) // 2]

# int8 and int4 variants measured back to back on the same random 7B:
# only one quantized tree is ever resident on the chip (int8 is 6.7 G
# of the 16 G; freed before the 3.4 G int4 tree transfers).
_out = {"model": "llama2-7b (random init), weight-only quant + int8 KV",
        "cache_len": _CL}
for _name, _qfn in (("int8", _quant), ("int4", _quant4)):
    with _jax.default_device(_jax.devices("cpu")[0]):
        _qh = _qfn(_p_host)
    if _name == "int4":
        # Last quantize consumed it: drop the ~13.4 GB bf16 host tree
        # now so it never overlaps the int4 transfer (ADVICE r5 —
        # keeping it resident across both passes nearly doubled peak
        # host memory on the TPU VM).
        del _p_host
    _qp = _jax.tree_util.tree_map(lambda a: _jax.device_put(a, _dev),
                                  _qh)
    del _qh; _gc.collect()
    _jax.block_until_ready(_jax.tree_util.tree_leaves(_qp)[0])
    _g1 = _mkgen(_cfg, _N1, max_len=_CL, kv_quantized=True)
    _g2 = _mkgen(_cfg, _N2, max_len=_CL, kv_quantized=True)
    int(_np.asarray(_g1(_qp, _prompt_for()))[0, -1])  # compile+first
    int(_np.asarray(_g2(_qp, _prompt_for()))[0, -1])
    _lo = _median_s(_g1, _qp)
    _hi = _median_s(_g2, _qp)
    _dt_tok = (_hi - _lo) / (_N2 - _N1)
    _w_bytes = sum(x.size * x.dtype.itemsize
                   for x in _jax.tree_util.tree_leaves(_qp))
    _bpt = _w_bytes + _kv_bytes
    _out[_name + "_weight_gb"] = round(_w_bytes / 1e9, 2)
    _out[_name + "_lo_hi_s"] = [round(_lo, 4), round(_hi, 4)]
    _out[_name + "_bytes_per_tok_gb"] = round(_bpt / 1e9, 2)
    if _dt_tok <= 0:
        _out[_name + "_tok_per_s"] = None     # noise won: say so
        _out[_name + "_ms_per_tok"] = None
        _out[_name + "_roofline_pct_v5e"] = None
    else:
        _out[_name + "_tok_per_s"] = round(1.0 / _dt_tok, 1)
        _out[_name + "_ms_per_tok"] = round(_dt_tok * 1e3, 2)
        _out[_name + "_roofline_pct_v5e"] = round(
            100.0 * (1.0 / _dt_tok) / (819e9 / _bpt), 1)
    del _qp, _g1, _g2; _gc.collect()
_out["int4_vs_int8"] = (
    round(_out["int4_tok_per_s"] / _out["int8_tok_per_s"], 2)
    if _out["int8_tok_per_s"] and _out["int4_tok_per_s"] else None)
_json.dumps(_out)
"""

# MoE dispatch-mode throughput: one train-step (loss+grads) per
# dispatch mode on a ~0.5B-expert MoE.  The dense one-hot dispatch
# materializes a (T, k, E, C) slot tensor — with C ~ cf*k*T/E that is
# O(T^2) MEMORY, terabytes at T = 8192 — so dense is measured only at
# a small token count (T = 512, where it is feasible), while sparse
# (sort/segment, linear) and dropless (ragged_dot) run the big shape
# too.  The small-shape three-way + big-shape pair together turn the
# dispatch-mode design (linear vs quadratic in tokens) into numbers.
MOE_CELL = """
import dataclasses, json as _json, time as _time
import jax as _jax, jax.numpy as _jnp
from nbdistributed_tpu.models.moe import (MoEConfig, init_moe_model,
                                          moe_loss_fn)
_DM, _DF, _NL, _B, _S, _steps = 1024, 2048, 8, 8, 1024, 3
_cfg0 = MoEConfig(vocab_size=32000, d_model=_DM, n_layers=_NL,
                  n_heads=16, n_kv_heads=4, d_ff=_DF,
                  max_seq_len=2048, n_experts=8, top_k=2,
                  dtype=_jnp.bfloat16, use_flash=True)
_p = init_moe_model(_jax.random.PRNGKey(0), _cfg0)
_out = {"capacity_factor": _cfg0.capacity_factor,
        "n_experts": _cfg0.n_experts, "top_k": _cfg0.top_k}

import numpy as _np
_seed = [1000]
def _measure(mode, B, S):
    # Per-step time = delta between a (1+_steps)-step and a 1-step
    # loop (median of 2 each), every step on FRESH token values and
    # every loop ending in a value fetch — same-input repeats are
    # served by the tunnel's result cache and block_until_ready is
    # async-acked, so the naive loop "measures" ~0.
    _cfg = dataclasses.replace(_cfg0, moe_dispatch=mode)
    _f = _jax.jit(_jax.grad(lambda p, b: moe_loss_fn(p, b, _cfg)))
    def _toks():
        _seed[0] += 1
        return _jax.random.randint(_jax.random.PRNGKey(_seed[0]),
                                   (B, S), 0, _cfg0.vocab_size)
    def _loop_s(_n):
        _ts = []
        for _ in range(2):
            _batches = [_toks() for _i in range(_n)]
            _t0 = _time.time()
            for _tk in _batches:
                _g = _f(_p, {"tokens": _tk})
            float(_np.asarray(
                _jax.tree_util.tree_leaves(_g)[0]).ravel()[0])
            _ts.append(_time.time() - _t0)
        return min(_ts)
    float(_np.asarray(_jax.tree_util.tree_leaves(
        _f(_p, {"tokens": _toks()}))[0]).ravel()[0])   # compile
    _dt = (_loop_s(1 + _steps) - _loop_s(1)) / _steps
    return None if _dt <= 0 else B * S / _dt           # noise: say so

_Bs, _Ss = max(1, _B // 4), max(32, _S // 4)       # small: T feasible
_out["small_tokens"] = _Bs * _Ss                    # for dense
for _mode in ("dense", "sparse", "dropless"):
    _tps = _measure(_mode, _Bs, _Ss)
    _out["small_" + _mode + "_tok_per_s"] = (
        None if _tps is None else round(_tps, 1))
_out["big_tokens"] = _B * _S
for _mode in ("sparse", "dropless"):
    _tps = _measure(_mode, _B, _S)
    _out["big_" + _mode + "_tok_per_s"] = (
        None if _tps is None else round(_tps, 1))
for _mode in ("sparse", "dropless"):
    _num = _out["small_" + _mode + "_tok_per_s"]
    _den = _out["small_dense_tok_per_s"]
    _out["small_" + _mode + "_vs_dense"] = (
        None if not _num or not _den else round(_num / _den, 2))
_json.dumps(_out)
"""

# all_reduce bus-bandwidth sweep; degenerates to an HBM on-device copy
# measurement on a 1-process world (labeled as such).
ALLREDUCE_CELL = """
import json as _json, time as _time
import jax as _jax, jax.numpy as _jnp
_rows = []
for _mib in (1, 4, 16, 64):
    _n = _mib * (1 << 20) // 4
    _x = _jax.random.normal(_jax.random.PRNGKey(_mib), (_n,),
                            _jnp.float32)
    _jax.block_until_ready(_x)
    if world_size > 1:
        _jax.block_until_ready(all_reduce(_x))      # warm the program
        _t0 = _time.time()
        for _i in range(5):
            # Vary the operand per call so a program+input result
            # cache can never serve a timed iteration (i+1: factor
            # 1.0 would replay the warm-up input bit-for-bit).
            _y = all_reduce(_x * (1.0 + (_i + 1) * 0.015625))
        float(_y[0])                                # value fetch
        _dt = (_time.time() - _t0) / 5
        _bus = 2 * (world_size - 1) / world_size * _mib / 1024 / _dt
        _rows.append({"mib": _mib, "s": round(_dt, 6),
                      "bus_gb_per_s_per_chip": round(_bus, 3)})
    else:
        # Chained scan delta (same pattern as the flash cell): the
        # carry feeds each +1.0, so per-iteration HBM read+write time
        # is (long-short chain)/delta with a value fetch at the end —
        # honest over the tunnel's async-ack/result-cache behavior.
        def _loop_s(_n):
            _g = _jax.jit(lambda a: _jax.lax.scan(
                lambda c, _: (c + 1.0, None), a, None, length=_n)[0])
            float(_g(_x).sum())                     # compile + first
            _ts = []
            for _i in range(3):
                _xi = _x * (1.0 + 0.0625 * (_i + 1))
                _t0 = _time.time()
                float(_g(_xi).sum())
                _ts.append(_time.time() - _t0)
            return sorted(_ts)[1]
        _dt = (_loop_s(12) - _loop_s(2)) / 10
        _rows.append({"mib": _mib, "s": round(_dt, 6),
                      "hbm_rw_gb_per_s": (
                          None if _dt <= 0 else
                          round(2 * _mib / 1024 / _dt, 1))})
_json.dumps({"mode": "bus" if world_size > 1 else
             "single_chip_hbm_bound", "rows": _rows})
"""


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def parse_result_json(resp) -> dict | None:
    """The cells above end in json.dumps(...), so the REPL echo is the
    repr of a JSON string."""
    out = resp.data.get("output", "")
    line = out.strip().splitlines()[-1] if out.strip() else ""
    try:
        return json.loads(ast.literal_eval(line))
    except Exception:
        return None


def _spawn_world(backend: str, world: int):
    """Spawn a worker world; returns (comm, pm) attached and ready."""
    from nbdistributed_tpu.manager import wait_until_ready
    comm = CommunicationManager(num_workers=world, timeout=300)
    pm = ProcessManager()
    try:
        pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
        pm.start_workers(world, comm.port, backend=backend)
        wait_until_ready(comm, pm, 150)
    except Exception:
        _teardown(comm, pm, world)
        raise
    return comm, pm


def _teardown(comm, pm, world: int) -> None:
    """Polite shutdown broadcast, then the tiered kill ladder, then the
    listener close.  BLOCKING (pm.shutdown waits through SIGTERM →
    SIGKILL), so by the time it returns no worker of this world can
    still be holding chip HBM when the next world spawns."""
    try:
        comm.post(list(range(world)), "shutdown")
        time.sleep(0.3)
    except Exception:
        pass
    try:
        pm.shutdown()
    except Exception:
        pass
    try:
        comm.shutdown()
    except Exception:
        pass


def _exec_measure(comm, name: str, cell: str, timeout: int) -> dict | None:
    """Run one measurement cell on rank 0; parse its trailing JSON."""
    resp = comm.send_to_ranks([0], "execute", cell, timeout=timeout)
    m = resp[0]
    if m.data.get("error"):
        log(f"[bench] {name} cell failed: "
            f"{m.data.get('traceback', m.data['error'])}")
        return None
    out = parse_result_json(m)
    if out is not None:
        log(f"[bench] {name}: {out}")
    return out


def measure_flight_recorder(comm, echoes: int = 40) -> dict:
    """ISSUE 3 numbers for the BENCH json: how many events this run's
    coordinator ring holds, the raw append cost, and the flight
    recorder's overhead on a control-plane echo round-trip measured
    directly — the same ``get_status`` echo with recording on
    (default) and forced off.  The acceptance bar is < 5 %: the append
    is microseconds against a multi-hundred-microsecond socket
    round-trip."""
    import statistics

    from nbdistributed_tpu.observability import flightrec

    out: dict = {"coordinator_events": len(comm.flight),
                 "ring_path": getattr(comm.flight, "path", None)}

    rec = flightrec.FlightRecorder(
        os.path.join(flightrec.run_dir(), "bench-micro.ring"))
    n = 20000
    t0 = time.perf_counter()
    for i in range(n):
        rec.record("dispatch", msg_id="0123456789abcdef",
                   type="execute", attempt=0)
    out["append_ns"] = round((time.perf_counter() - t0) / n * 1e9)
    rec.close()

    def _echo_s() -> float:
        t0 = time.perf_counter()
        comm.send_to_ranks([0], "get_status", timeout=60)
        return time.perf_counter() - t0

    def _median_echo() -> float:
        return statistics.median(_echo_s() for _ in range(echoes))

    def _worker_flight(enabled: bool) -> None:
        # BOTH ends record on the echo path (coordinator 'send',
        # worker 'dispatch'): the no-record leg must silence the
        # worker's ring too or the comparison hides half the cost.
        comm.send_to_ranks(
            [0], "execute",
            "import nbdistributed_tpu.observability.flightrec as _f\n"
            f"_f.recorder().enabled = {enabled}", timeout=60)

    _median_echo()                      # warm both paths
    on_s = _median_echo()
    comm.flight.enabled = False
    _worker_flight(False)
    try:
        off_s = _median_echo()
    finally:
        comm.flight.enabled = True
        _worker_flight(True)
    out["echo_us_record"] = round(on_s * 1e6, 1)
    out["echo_us_norecord"] = round(off_s * 1e6, 1)
    out["echo_overhead_pct"] = round((on_s - off_s) / off_s * 100, 2) \
        if off_s > 0 else None
    return out


def measure_pipeline(comm, world: int, k: int = 24,
                     ddp_steps: int = 12) -> dict:
    """ISSUE 14 numbers: per-cell dispatch overhead under the three
    dispatch modes on the SAME cells, so the differences are pure
    control plane —

    * ``sync``: today's send-and-wait per cell (k round trips);
    * ``async``: k cells streamed through ``comm.submit`` with one
      wait at the end (the in-flight-window wire path; admission
      gating lives a layer up and adds nothing for independent
      cells);
    * ``repeat``: ONE dispatch that loops k steps worker-side
      (``%%distributed --repeat k``) — the amortization bound.

    Reported per-cell/per-step in ms for a trivial cell (pure
    dispatch overhead) and as steps/s for the cell-wise DDP
    ``STEP_CELL`` (the headline BENCH metric's three modes).  Runs on
    CPU worlds too — the row is BENCH-comparable everywhere; the
    <0.1 ms/step target is judged on the next live TPU window.
    """
    trivial = "_pipe = 1 + 1"
    ranks = list(range(world))

    def _sync(cell: str, n: int) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            comm.send_to_all("execute", cell, timeout=600)
        return time.perf_counter() - t0

    def _async(cell: str, n: int) -> float:
        t0 = time.perf_counter()
        handles = [comm.submit(ranks, "execute", cell, timeout=600)
                   for _ in range(n)]
        for h in handles:
            h.wait()
        return time.perf_counter() - t0

    def _repeat(cell: str, n: int) -> float:
        t0 = time.perf_counter()
        resp = comm.send_to_all(
            "execute", {"code": cell, "target_ranks": ranks,
                        "repeat": n}, timeout=600)
        for m in resp.values():
            if m.data.get("error"):
                raise RuntimeError(m.data["error"])
        return time.perf_counter() - t0

    # Warm each path once so compile/first-dispatch costs don't skew
    # the per-mode comparison.
    comm.send_to_all("execute", trivial, timeout=600)
    out: dict = {"cells": k, "ddp_steps": ddp_steps}
    sync_s = _sync(trivial, k)
    async_s = _async(trivial, k)
    rep_s = _repeat(trivial, k)
    out["dispatch_ms_per_cell"] = {
        "sync": round(sync_s / k * 1e3, 3),
        "async": round(async_s / k * 1e3, 3),
        "repeat": round(rep_s / k * 1e3, 3),
    }
    out["overlap_speedup"] = round(sync_s / async_s, 2) \
        if async_s > 0 else None

    # Cell-wise DDP under each mode: the headline metric's three
    # dispatch disciplines on the real local_step cell.
    ddp = {}
    for name, fn in (("sync", _sync), ("async", _async),
                     ("repeat", _repeat)):
        try:
            el = fn(STEP_CELL, ddp_steps)
            ddp[name] = round(ddp_steps / el, 2)
        except Exception as e:
            log(f"[bench] pipeline ddp/{name} failed: {e}")
            ddp[name] = None
    out["ddp_steps_per_s"] = ddp
    if ddp.get("sync") and ddp.get("repeat"):
        # How much of the worker-local loop's rate cell-wise dispatch
        # reaches per mode — the "within 10% of a worker-local loop"
        # acceptance ratio, measurable every run.
        out["vs_worker_local_loop"] = {
            m: round(v / ddp["repeat"], 3)
            for m, v in ddp.items() if v}
    return out


def measure_telemetry_peaks(comm) -> dict:
    """Peak-HBM summary from the heartbeat-piggybacked telemetry
    snapshots the coordinator accumulated during the run — the device-
    memory-over-time trajectory for the BENCH json."""
    from nbdistributed_tpu.observability import telemetry as _tel

    peaks = {}
    last = {}
    for r in range(comm.num_workers):
        hist = comm.telemetry_history(r)
        if not hist:
            continue
        p = _tel.peak_hbm(hist)
        if p:
            peaks[str(r)] = p
        snap = hist[-1]
        last[str(r)] = {k: snap.get(k)
                        for k in ("bufs", "compiles", "compile_s")
                        if snap.get(k) is not None}
    out = {}
    if peaks:
        out["peak_hbm_bytes"] = peaks
    if last:
        out["last_snapshot"] = last
    return out


# Sentinel: measure_family could not even attach a worker — the signal
# run_families uses to distinguish "this cell failed" (keep going) from
# "the accelerator tunnel is gone" (stop burning attach timeouts).
SPAWN_FAILED = object()


def measure_family(backend: str, name: str, cell: str, timeout: int):
    """Run ONE measurement family in its own fresh worker process.

    Per-measurement process isolation is the bench rule, learned the
    hard way: round 3's only on-chip flash sample measured 0.065x vs
    XLA inside a worker whose HBM a previously-OOMed 1B train cell had
    filled — no amount of in-process cleanup (namespace sweeps,
    jax.clear_caches, live-array deletion) reliably un-poisons a
    wedged allocator, and a contaminated number is worse than none.
    The worker is spawned fresh, runs exactly one measurement cell,
    and is torn down (blocking) before the next family starts, so no
    family can see another's leftovers.

    Returns the parsed result dict, None (cell failed — measurement
    lost but the world is healthy), or :data:`SPAWN_FAILED` (no worker
    attached at all).
    """
    log(f"[bench] {name}: spawning fresh worker")
    try:
        comm, pm = _spawn_world(backend, 1)
    except Exception as e:
        log(f"[bench] {name} skipped (spawn failed): {e}")
        return SPAWN_FAILED
    try:
        return _exec_measure(comm, name, cell, timeout)
    except Exception as e:
        log(f"[bench] {name} skipped: {e}")
        return None
    finally:
        _teardown(comm, pm, 1)


def tpu_families():
    """(name, cell, timeout) per TPU measurement family — shared by
    the full run and the NBD_BENCH_ONLY re-measure mode."""
    return (
        # Flagship MFU (135M — the reference demo scale).
        ("smol135m", MFU_CELL.format(
            peak=V5E_PEAK_BF16, shape="(8, 2048, 10)", reps="(3, 2)",
            tr_start="2 * _B", extra_cfg="",
            cfg_name="smol_135m_config"), 2400),
        # MFU at a scale where MFU means something: ~1.1B params,
        # d_model=2048 — GEMMs a v5e MXU can fill.
        ("tinyllama_1b", MFU_CELL.format(
            peak=V5E_PEAK_BF16, shape="(8, 2048, 5)", reps="(3, 2)",
            tr_start="2 * _B", extra_cfg="",
            cfg_name="tinyllama_1b_config"), 2400),
        # Long-context single-chip training: S=8192 with per-layer
        # remat; the policy table (and the ce_chunk row — at S=8192
        # the fp32 logits alone are 1.6 G/row) lands alongside.
        ("smol135m_s8192", MFU_CELL.format(
            peak=V5E_PEAK_BF16, shape="(1, 8192, 3)", reps="(3, 2)",
            tr_start="2 * _B", extra_cfg=", max_seq_len=8192",
            cfg_name="smol_135m_config"), 2400),
        # Kernel-vs-XLA only where the kernel compiles (interpret
        # mode on CPU is orders slower by design).
        ("flash_attn", FLASH_CELL, 900),
        ("decode", DECODE_CELL, 1200),
        # +2 compiles for the int4-draft row.
        ("speculative", SPEC_CELL, 1500),
        # Prefix-admission measurement added two more server worlds
        # (extra prefill/absorb compiles) — budget accordingly.
        ("serving", SERVE_CELL, 1800),
        # ~10 G of quantized weights (int8 then int4 trees) cross the
        # tunnel at unknown bandwidth and four generate programs
        # compile at 7B: budget wide.
        ("decode_7b_int8", DECODE7B_CELL, 3000),
        # MoE dispatch modes (dense/sparse/dropless train-step
        # throughput at the same routing) — evidences the dispatch
        # design (linear vs quadratic in tokens) on silicon.
        ("moe_dispatch", MOE_CELL, 1800),
    )


def run_families_only(names: list[str]) -> int:
    """NBD_BENCH_ONLY mode: re-measure the named families (each in a
    fresh worker) and MERGE the results into BENCH_TPU_LAST.json.

    The watcher uses this after tune_flash.py lands a tuned block
    table: fresh workers import the tuned sizes, so re-running just
    the kernel families captures the post-tuning numbers without
    paying for a full bench pass."""
    backend = topology.detect_backend()
    if backend != "tpu":
        log(f"[bench] NBD_BENCH_ONLY needs a TPU backend, "
            f"detected {backend}")
        return 1
    unknown = [n for n in names
               if n not in {f[0] for f in tpu_families()}]
    if unknown:
        log(f"[bench] unknown families {unknown}; known: "
            f"{[f[0] for f in tpu_families()]}")
        return 1
    extra: dict = {}
    fams = [f for f in tpu_families() if f[0] in names]
    run_families(backend, fams, extra)
    result = {"metric": "bench_families_remeasure_tpu",
              "value": len(extra), "unit": "families",
              "vs_baseline": 1.0, "extra": extra}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_TPU_LAST.json")
    try:
        with open(path) as f:
            snap = json.load(f)
        snap.setdefault("result", {}).setdefault("extra", {}).update(
            extra)
        ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        snap["remeasured_at"] = ts
        snap["remeasured_families"] = sorted(extra)
        snap.setdefault("family_measured_at", {}).update(
            {k: ts for k in extra})
        # A family just re-measured is no longer carried stale data.
        snap["carried_from_previous"] = [
            k for k in snap.get("carried_from_previous", [])
            if k not in extra]
        with open(path + ".tmp", "w") as f:
            json.dump(snap, f, indent=1)
        os.replace(path + ".tmp", path)
    except (OSError, ValueError) as e:
        log(f"[bench] could not merge into snapshot: {e}")
    print(json.dumps(result), flush=True)
    return 0


def persist_tpu_snapshot(path: str, result: dict, extra: dict,
                         stamp=None) -> dict:
    """Atomically write BENCH_TPU_LAST.json, MERGING per-family over
    the previous snapshot: families the tunnel died before
    re-measuring are carried forward with their original timestamps
    (``family_measured_at`` / ``carried_from_previous`` keep the
    record honest) — a partial window must never erase a fuller
    earlier capture.

    ``stamp``: names measured at THIS moment (the incremental
    per-family persist passes just the family that finished, so
    earlier families keep their real measurement times).  Default
    (None) stamps every key of ``extra``; keys never stamped before
    are stamped regardless."""
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    prev_extra, fam_ts, prev_ts = {}, {}, None
    try:
        with open(path) as f:
            prev = json.load(f)
        prev_extra = prev.get("result", {}).get("extra", {})
        fam_ts = prev.get("family_measured_at", {})
        prev_ts = prev.get("measured_at")
    except (OSError, ValueError):
        pass
    carried = sorted(k for k in prev_extra if k not in extra)
    fam_ts.update({k: now
                   for k in (extra if stamp is None else stamp)})
    for k in extra:
        fam_ts.setdefault(k, now)      # first sighting of this key
    for k in carried:
        fam_ts.setdefault(k, prev_ts)
    snap_result = dict(result)
    snap_result["extra"] = {**prev_extra, **extra}
    snap = {"measured_at": now,
            "family_measured_at": fam_ts,
            "carried_from_previous": carried,
            "result": snap_result}
    with open(path + ".tmp", "w") as f:
        json.dump(snap, f, indent=1)
    os.replace(path + ".tmp", path)   # atomic
    return snap


def run_families(backend: str, families, extra: dict,
                 measure=None, on_family=None) -> None:
    """Run measurement families, each in a fresh process, filling
    ``extra[name]``.  Bails out after two consecutive spawn failures:
    a wedged tunnel would otherwise cost the full ~150 s attach
    timeout per remaining family, serially — minutes of dead time
    that can push the bench past the driver's outer deadline.

    ``on_family(name)`` fires after every successful measurement — the
    TPU path persists the snapshot there, so a window (or outer
    timeout) dying mid-run keeps every family already measured.

    ``NBD_BENCH_FAMILY_BUDGET_S`` (default 5400) bounds the whole
    family stage: once exceeded, remaining families are skipped with a
    loud log instead of risking the driver's outer deadline killing
    the run before its one JSON line prints — the per-family snapshot
    still holds everything measured, and earlier windows' families
    ride it as carried entries."""
    measure = measure if measure is not None else measure_family
    try:
        budget = float(knobs.get_raw("NBD_BENCH_FAMILY_BUDGET_S",
                                     "5400"))
    except ValueError:
        log("[bench] NBD_BENCH_FAMILY_BUDGET_S is not a number; "
            "using 5400")
        budget = 5400.0
    t_start = time.time()
    spawn_failures = 0
    families = list(families)
    for i, (name, cell, cell_timeout) in enumerate(families):
        elapsed = time.time() - t_start
        if elapsed > budget:
            log(f"[bench] family budget {budget:.0f}s exhausted after "
                f"{elapsed:.0f}s — skipping "
                f"{[n for n, _, _ in families[i:]]}")
            return
        out = measure(backend, name, cell, cell_timeout)
        if out is SPAWN_FAILED:
            spawn_failures += 1
            if spawn_failures >= 2:
                log("[bench] two consecutive spawn failures — tunnel "
                    "presumed down, skipping remaining families")
                return
            continue
        spawn_failures = 0
        if out is not None:
            extra[name] = out
            if on_family is not None:
                try:
                    on_family(name)
                except Exception as e:
                    log(f"[bench] on_family({name}) failed: {e}")


# Elastic-pool family (ISSUE 16): a deliberately odd-shaped jit so
# neither the in-memory nor a stale persistent cache can pre-own it —
# the SAME cell runs cold on a fresh pool, then again on a
# resized-in fleet whose persistent compile cache should serve it
# warm.  The final expression is the worker-side compile+run seconds.
ELASTIC_COMPILE_CELL = """
import time as _t
import jax as _jax, jax.numpy as _jnp
_t0 = _t.time()
_f = _jax.jit(lambda x: _jnp.tanh(x @ x.T).sum()
              + _jnp.sin(x).mean())
_x = _jnp.ones((521, 517), _jnp.float32)
float(_f(_x))
_t.time() - _t0
"""


def measure_elastic() -> dict | None:
    """The ISSUE 16 numbers: cold vs warm first-cell seconds (the
    persistent compile cache serving a resized-in worker), the resize
    drain-barrier and whole-flip wall-clock, and a tenant migration
    end to end between two pools under one runs root.

    Always measured on the CPU backend in pools of its own (the
    mechanism under test is the control plane + XLA cache, not the
    accelerator), AFTER the pooled bench world is gone."""
    import shutil
    import tempfile

    from nbdistributed_tpu.gateway import router as router_mod
    from nbdistributed_tpu.gateway.client import TenantClient
    from nbdistributed_tpu.gateway.daemon import GatewayDaemon
    from nbdistributed_tpu.gateway.scheduler import SchedPolicy

    runs_root = tempfile.mkdtemp(prefix="nbd-bench-elastic-")
    run_a = os.path.join(runs_root, "pool-a")
    run_b = os.path.join(runs_root, "pool-b")
    os.makedirs(run_a)
    os.makedirs(run_b)
    saved = os.environ.get("NBD_RUN_DIR")
    gw_a = gw_b = client = None
    out: dict = {"backend": "cpu"}

    def _cell_seconds(cl) -> float:
        r = cl.execute(ELASTIC_COMPILE_CELL, target_ranks=[0],
                       timeout=300)
        res = (r.get("results") or {}).get("0") or {}
        if r.get("error") or res.get("error"):
            raise RuntimeError(r.get("error") or res["error"])
        return float(ast.literal_eval(res["output"]))

    try:
        os.environ["NBD_RUN_DIR"] = run_a
        gw_a = GatewayDaemon(
            1, backend="cpu",
            policy=SchedPolicy("fair", mesh_slots=1,
                               tenant_inflight=8, queue_depth=16),
            request_timeout=None, attach_timeout=240.0)
        client = TenantClient(gw_a.tenant_host, gw_a.tenant_port,
                              "bench", pool_token=gw_a.pool_token)
        out["cold_first_cell_s"] = round(_cell_seconds(client), 4)

        res = gw_a.resize(2, reason="bench")
        if res.get("status") != "resized":
            raise RuntimeError(f"resize failed: {res}")
        out["resize_drain_s"] = res["drain_s"]
        out["resize_wall_s"] = res["wall_s"]
        # Fresh processes, wiped namespaces — only the persistent
        # cache can make this fast.
        out["warm_first_cell_s"] = round(_cell_seconds(client), 4)
        if out["warm_first_cell_s"] > 0:
            out["warm_speedup"] = round(
                out["cold_first_cell_s"] / out["warm_first_cell_s"],
                2)
        client.close()
        client = None

        os.environ["NBD_RUN_DIR"] = run_b
        gw_b = GatewayDaemon(
            1, backend="cpu",
            policy=SchedPolicy("fair", mesh_slots=1,
                               tenant_inflight=8, queue_depth=16),
            request_timeout=None, attach_timeout=240.0)
        t0 = time.time()
        router_mod.migrate_tenant("bench", run_a, run_b, force=True)
        out["migrate_s"] = round(time.time() - t0, 4)
        return out
    finally:
        if client is not None:
            try:
                client.close()
            except Exception:
                pass
        for gw in (gw_b, gw_a):
            if gw is not None:
                try:
                    gw.close()
                except Exception:
                    pass
        if saved is None:
            os.environ.pop("NBD_RUN_DIR", None)
        else:
            os.environ["NBD_RUN_DIR"] = saved
        shutil.rmtree(runs_root, ignore_errors=True)


SERVE_SPEC_CELL = (
    "import jax as _j, jax.numpy as _jn\n"
    "from nbdistributed_tpu.models import tiny_config, init_params\n"
    "cfg = tiny_config(dtype=_jn.float32, use_flash=False)\n"
    "params = init_params(_j.random.PRNGKey(0), cfg)\n")


def measure_serving() -> dict | None:
    """The ISSUE 17 serving-fast-path numbers from the closed-loop
    load harness: sustained tokens/s with client-observed p99
    TTFT/TPOT, then the shed rate at 2x the measured sustainable
    request rate — all through the real tenant plane (the exact core
    ``tools/nbd_loadgen.py`` runs) against a paged, multi-rank decode
    plane.

    CPU backend in a pool of its own (the mechanism under test is the
    serving control plane, not the accelerator), AFTER the pooled
    bench world is gone."""
    import shutil
    import tempfile

    from nbdistributed_tpu.gateway.client import TenantClient
    from nbdistributed_tpu.gateway.daemon import GatewayDaemon
    from nbdistributed_tpu.gateway.scheduler import SchedPolicy
    from nbdistributed_tpu.serving_fast import LoadConfig, run_load

    run_dir = tempfile.mkdtemp(prefix="nbd-bench-serving-")
    saved = os.environ.get("NBD_RUN_DIR")
    gw = client = None
    out: dict = {"backend": "cpu"}

    def _load(cl, rps: float, duration: float) -> dict:
        from nbdistributed_tpu.serving_fast.loadgen import (
            ClientTransport)
        cfg = LoadConfig(rps=rps, duration_s=duration,
                         arrival="poisson", seed=7,
                         prompt_len=(4, 12), max_new=(4, 10),
                         drain_s=120.0)
        return run_load(ClientTransport(cl), cfg)

    try:
        os.environ["NBD_RUN_DIR"] = run_dir
        gw = GatewayDaemon(
            2, backend="cpu",
            policy=SchedPolicy("fair", mesh_slots=1,
                               tenant_inflight=64, queue_depth=64),
            request_timeout=None, attach_timeout=240.0)
        client = TenantClient(gw.tenant_host, gw.tenant_port,
                              "loadgen", pool_token=gw.pool_token)
        client.serve_start(SERVE_SPEC_CELL, max_batch=4, max_len=48,
                           pad_to=4, steps=4, queue_depth=8,
                           inflight=64, decode_ranks=2,
                           kv_block_tokens=8, timeout=600)
        # Sustained phase: modest offered rate, everything completes.
        rep = _load(client, rps=2.0, duration=8.0)
        out["tokens_per_s"] = rep["tokens_per_s"]
        out["p99_ttft_ms"] = (rep["client"]["ttft_ms"]
                              or {}).get("p99")
        out["p99_tpot_ms"] = (rep["client"]["tpot_ms"]
                              or {}).get("p99")
        out["sustained_completed"] = rep["completed"]
        out["sustained_hung"] = rep["hung"]
        # Overload phase: 2x the COMPLETION rate the plane just
        # demonstrated (floor 2x offered) — the bounded queue must
        # shed with explicit verdicts, not hang.
        sustainable = max(rep["completed"] / max(rep["duration_s"],
                                                 1e-9), 2.0)
        rep2 = _load(client, rps=2.0 * sustainable, duration=6.0)
        out["overload_rps"] = round(2.0 * sustainable, 2)
        out["overload_shed_rate"] = rep2["shed_rate"]
        out["overload_completed"] = rep2["completed"]
        out["overload_hung"] = rep2["hung"]
        st = client.serve_status()
        kv = st.get("kv") or {}
        if kv:
            out["kv_block_tokens"] = kv.get("block_tokens")
            out["kv_blocks_per_rank"] = kv.get("blocks_per_rank")
        # Score the sustained phase against the checked-in perf
        # baseline (ISSUE 18) so a BENCH run carries the same
        # regression verdict CI's perfwatch gate would give —
        # reported, not enforced (the CI job owns the exit code).
        try:
            from nbdistributed_tpu.observability import perfbase
            doc = perfbase.load_baselines("BENCH_BASELINES.json")
            base = (doc.get("baselines") or {}).get("serving_smoke")
            if base:
                res = perfbase.score(base, perfbase.extract_metrics(
                    rep, (st.get("lat") or {}).get("summary")))
                out["perfwatch"] = {"pass": res["pass"],
                                    "regressions": res["regressions"]}
        except Exception:
            pass
        return out
    finally:
        if client is not None:
            try:
                client.serve_stop()
            except Exception:
                pass
            try:
                client.close()
            except Exception:
                pass
        if gw is not None:
            try:
                gw.close()
            except Exception:
                pass
        if saved is None:
            os.environ.pop("NBD_RUN_DIR", None)
        else:
            os.environ["NBD_RUN_DIR"] = saved
        shutil.rmtree(run_dir, ignore_errors=True)


def measure_trainguard() -> dict | None:
    """The ISSUE 19 training-integrity-guard numbers: guarded vs
    unguarded DDP step rate at the default audit/snapshot cadences,
    plus the cost of one replica-consistency audit step (the param
    fingerprint fold).  The acceptance bar is guarded overhead <10%:
    the device-side finite gate rides the compiled step and the host
    side resolves verdicts one step late, so the steady-state cost is
    a deque rotation plus an already-materialized scalar read.

    CPU, in-process: the mechanism under test is the guard
    orchestration, not the accelerator."""
    import time as _time

    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from nbdistributed_tpu.parallel import data_parallel
    from nbdistributed_tpu.parallel import mesh as mesh_mod
    from nbdistributed_tpu.resilience import trainguard as tg

    n_steps = 600
    m = mesh_mod.make_mesh({"dp": 1})

    def loss_fn(params, batch):
        x, y = batch
        pred = jnp.tanh(x @ params["w1"]) @ params["w2"]
        return jnp.mean((pred - y) ** 2)

    key = jax.random.PRNGKey(0)
    k1, k2, kx = jax.random.split(key, 3)
    params = {"w1": jax.random.normal(k1, (256, 256), jnp.float32) * 0.05,
              "w2": jax.random.normal(k2, (256, 64), jnp.float32) * 0.05}
    opt = optax.adam(1e-3)
    # Batch 256 (= the hidden width): the guard's device-side work —
    # the fp32 grad-norm² reduction and the cond's grad
    # materialization — is O(params) and batch-INdependent, while the
    # step's useful compute scales with the batch.  A 64-row batch
    # over an 81K-param model makes the step artificially tiny
    # relative to that fixed cost and measures mostly dispatch noise;
    # square batches are the representative operating point.
    batch = (jax.random.normal(kx, (256, 256)), jnp.zeros((256, 64)))

    def make_runner(guard: bool):
        # Fresh copies: replicate() aliases when the sharding already
        # matches, and the donating step would eat the template tree.
        p, _ = data_parallel.ddp_init(
            jax.tree_util.tree_map(jnp.copy, params), None, m)
        s = jax.jit(opt.init)(p)
        step = data_parallel.make_ddp_step(loss_fn, opt, m, guard=guard)
        if guard:
            g = tg.TrainGuard(step, p, s, rank=0)

            def run(n: int) -> None:
                loss = None
                for _ in range(n):
                    loss = g.step(batch)
                jax.block_until_ready(loss)

            return run, g.finish
        state = [p, s]

        def run(n: int) -> None:
            p, s = state
            for _ in range(n):
                p, s, loss = step(p, s, batch)
            state[:] = [p, s]
            jax.block_until_ready(loss)

        return run, (lambda: None)

    # The CPU here is shared and noisy (identical reps vary by >20%),
    # so back-to-back whole-loop timings compare different wall-clock
    # windows and the noise swamps the signal.  Interleave the two
    # loops in small slices instead: any interference burst lands on
    # both sides roughly equally, and the *ratio* — the number under
    # acceptance — stays honest.  The guarded side still steps its own
    # counter, so the default audit/snapshot cadences fire exactly as
    # they would in a straight run.
    run_u, fin_u = make_runner(guard=False)
    run_g, fin_g = make_runner(guard=True)
    # Warm the guarded runner PAST its first audit+snapshot (default
    # cadence 50): the first post-step snapshot re-specializes the
    # jitted tree copy for the stepped opt state's layouts, a one-time
    # per-process compile that a 200-step microbenchmark would
    # otherwise misread as recurring audit cost.
    run_u(55)
    run_g(55)
    # Per-side throughput = chunk size over the MINIMUM chunk time
    # (standard timeit practice): interference only ever adds time, so
    # the fastest of many small interleaved chunks estimates each
    # side's uncontended cost — medians still carried 5-10 points of
    # run-to-run jitter on this box.  The chunk equals the default
    # audit/snapshot cadence (50), so EVERY guarded chunk carries
    # exactly one audit + one snapshot — the minimum cannot dodge the
    # event cost the acceptance bar is about.
    chunk = 50
    ts_u: list[float] = []
    ts_g: list[float] = []
    for _ in range(n_steps // chunk):
        t0 = _time.perf_counter()
        run_u(chunk)
        ts_u.append(_time.perf_counter() - t0)
        t0 = _time.perf_counter()
        run_g(chunk)
        ts_g.append(_time.perf_counter() - t0)
    fin_g()
    fin_u()
    base = chunk / min(ts_u)
    guarded = chunk / min(ts_g)
    # One audit step's cost in isolation: fingerprint fold over the
    # params (world=1, so the gather/vote legs are the short-circuit).
    p, _ = data_parallel.ddp_init(
        jax.tree_util.tree_map(jnp.copy, params), None, m)
    tg.tree_fingerprint(p)  # compile
    t0 = _time.perf_counter()
    reps = 5
    for _ in range(reps):
        tg.tree_fingerprint(p)
    audit_ms = (_time.perf_counter() - t0) / reps * 1000.0
    return {"backend": "cpu", "steps": n_steps,
            "steps_per_s_unguarded": round(base, 2),
            "steps_per_s_guarded": round(guarded, 2),
            "overhead_pct": round((base - guarded) / base * 100.0, 2),
            "audit_step_ms": round(audit_ms, 3)}


def measure_transfer() -> dict | None:
    """The ISSUE 20 numbers: bulk-plane push/pull throughput — the
    chunked streaming protocol vs one legacy frame — plus the
    per-chunk compression ratio on compressible data.  CPU loopback,
    1-worker world of its own: the mechanism under test is the
    chunked wire protocol (flow control, crc, assembly copies), not
    the accelerator or a real NIC."""
    import numpy as np

    from nbdistributed_tpu.messaging import xfer

    size = 64 << 20
    out: dict = {"backend": "cpu", "bytes": size}
    rng = np.random.default_rng(0)
    incompressible = rng.integers(0, 256, size, dtype=np.uint8)
    comm = pm = None
    try:
        comm, pm = _spawn_world("cpu", 1)

        t0 = time.time()
        st = xfer.push_value(comm, [0], "xb", incompressible)
        out["push_chunked_gb_s"] = round(size / (time.time() - t0)
                                         / 1e9, 3)
        out["chunks"] = st["chunks"]
        out["inflight_peak_mb"] = round(
            st["inflight_peak_bytes"] / 1e6, 1)

        t0 = time.time()
        comm.send_to_ranks([0], "set_var", {"name": "xl"},
                           bufs={"value": incompressible},
                           timeout=xfer.scaled_timeout(size))
        out["push_legacy_gb_s"] = round(size / (time.time() - t0)
                                        / 1e9, 3)

        t0 = time.time()
        _, stats = xfer.pull_value(comm, 0, "xb")
        out["pull_chunked_gb_s"] = round(size / (time.time() - t0)
                                         / 1e9, 3)
        out["pull_resent_chunks"] = stats["resent_chunks"]

        t0 = time.time()
        resp = comm.send_to_rank(0, "get_var", "xl",
                                 timeout=xfer.scaled_timeout(size))
        np.asarray(resp.bufs["value"])  # materialize the decode view
        out["pull_legacy_gb_s"] = round(size / (time.time() - t0)
                                        / 1e9, 3)

        # Compression ratio on low-entropy data (repeated-pattern
        # bytes — the shape of embedding tables / quantized state),
        # forced through the always-available stdlib codec.
        compressible = np.tile(np.arange(256, dtype=np.uint8),
                               size // 256)
        saved = os.environ.get("NBD_XFER_CODEC")
        os.environ["NBD_XFER_CODEC"] = "zlib"
        try:
            st = xfer.push_value(comm, [0], "xc", compressible)
        finally:
            if saved is None:
                os.environ.pop("NBD_XFER_CODEC", None)
            else:
                os.environ["NBD_XFER_CODEC"] = saved
        out["compress_codec"] = st["codec"]
        out["compress_ratio"] = round(
            st["bytes"] / max(1, st["wire_bytes"]), 2)
        out["push_zlib_gb_s"] = round(
            size / max(1e-9, st["seconds"]) / 1e9, 3)
        out["codecs_available"] = xfer.available_codecs()
        return out
    finally:
        if comm is not None:
            _teardown(comm, pm, 1)


def main() -> int:
    # A SIGTERM (e.g. an outer `timeout` expiring) must tear down the
    # spawned workers: raising SystemExit lets run()'s finally-block
    # ProcessManager.shutdown() execute.  An orphaned worker keeps its
    # HBM allocations alive and poisons every later run on the shared
    # chip with RESOURCE_EXHAUSTED (observed on-chip this round).
    import signal

    def _term(signum, frame):
        raise SystemExit(143)

    signal.signal(signal.SIGTERM, _term)
    only = knobs.get_str("NBD_BENCH_ONLY")
    if only:
        return run_families_only(
            [n.strip() for n in only.split(",") if n.strip()])
    backend = topology.detect_backend()
    # World size: NBD_BENCH_WORLD env overrides; default is one worker
    # per TPU chip on this host (the bench host has 1), or 2 CPU/gloo
    # workers so the DDP all_reduce branch is a real cross-process
    # collective.
    default_world = "1" if backend == "tpu" else "2"
    world = int(knobs.get_raw("NBD_BENCH_WORLD", default_world))
    if backend == "tpu":
        for i, delay in enumerate(TPU_ATTEMPTS):
            if delay:
                log(f"[bench] backing off {delay}s before TPU attempt "
                    f"{i + 1}/{len(TPU_ATTEMPTS)}")
                time.sleep(delay)
            rc = run("tpu", world, attempt=i + 1)
            if rc == 0:
                return 0
            log(f"[bench] TPU attempt {i + 1} failed")
        # A flaky tunnel must not leave the driver without a number:
        # rerun on a 2-process CPU/gloo world (the metric name carries
        # the backend, so the JSON line stays honest about what ran).
        log("[bench] all TPU attempts failed; falling back to cpu world")
        return run("cpu", max(2, world))
    return run(backend, world)


def run(backend: str, world: int, attempt: int = 1) -> int:
    log(f"[bench] backend={backend} world={world} attempt={attempt}")

    comm = pm = None
    try:
        comm, pm = _spawn_world(backend, world)
        log("[bench] workers attached; running setup cell")
        resp = comm.send_to_all("execute", SETUP, timeout=600)
        for r, m in resp.items():
            if m.data.get("error"):
                log(f"[bench] setup failed on rank {r}: "
                    f"{m.data['traceback']}")
                return 1

        for _ in range(WARMUP):
            comm.send_to_all("execute", STEP_CELL, timeout=600)

        # compute = worker-side measured duration (excludes the control
        # plane), collected from the same steps we time end-to-end
        durations = []
        t0 = time.time()
        for i in range(STEPS):
            resp = comm.send_to_all("execute", STEP_CELL, timeout=600)
            for r, m in resp.items():
                if m.data.get("error"):
                    log(f"[bench] step {i} failed on rank {r}")
                    return 1
            durations.append(max(m.data["duration_s"]
                                 for m in resp.values()))
        elapsed = time.time() - t0
        steps_per_s = STEPS / elapsed
        durations.sort()
        compute = durations[len(durations) // 2]
        overhead_ms = (elapsed / STEPS - compute) * 1000

        # Reference architectural floor: 100ms display poll + 100ms ZMQ
        # poll per cell (SURVEY §3.2) on top of the same compute.
        ref_floor_steps_per_s = 1.0 / (0.2 + compute)
        vs_baseline = steps_per_s / ref_floor_steps_per_s
        log(f"[bench] {STEPS} cell-steps in {elapsed:.2f}s; "
            f"compute={compute*1000:.2f}ms/step, "
            f"framework overhead={overhead_ms:.2f}ms/step")

        extra: dict = {"overhead_ms_per_cell": round(overhead_ms, 3)}

        # Async pipelined dispatch (ISSUE 14): the same cells under
        # sync vs streamed-window vs --repeat dispatch, BEFORE the
        # latency snapshot below so the async cells' stage records
        # land in extra.latency_stages — the waterfall then shows the
        # overlap (pipelined cells book predecessor-wait as `queue`).
        try:
            pipe = measure_pipeline(comm, world)
            extra["pipeline"] = pipe
            log(f"[bench] pipeline: {pipe}")
        except Exception as e:
            log(f"[bench] pipeline measurement skipped: {e}")

        # Stage-latency decomposition of the cells just timed (ISSUE
        # 13): WHERE the per-cell overhead goes (queue/wire/dispatch/
        # compile/execute/reply/deliver p50-p99), so BENCH_* rows can
        # track dispatch-overhead decomposition across PRs instead of
        # one opaque overhead number.
        try:
            lat = comm.lat.summary()
            if lat.get("count"):
                extra["latency_stages"] = lat
                log(f"[bench] latency stages (ms, p50): "
                    + ", ".join(f"{s}={v['p50']}" for s, v in
                                lat["stages"].items()))
        except Exception as e:
            log(f"[bench] latency-stage snapshot skipped: {e}")

        # The context measurements below are best-effort: a failure
        # there must not discard the already-measured primary metric
        # (the whole point of the fallback ladder is that a JSON line
        # always comes out).
        if backend != "tpu":
            # CPU fallback: keep the MFU probe in the pooled world
            # (process contamination is an HBM phenomenon; host RAM is
            # plentiful and fallback runs should stay quick).
            try:
                log("[bench] measuring smol-135M fwd/train on rank 0")
                mfu = _exec_measure(
                    comm, "smol135m",
                    MFU_CELL.format(peak=1e30, shape="(2, 512, 3)",
                                    reps="(1, 1)", tr_start="_B",
                                    extra_cfg="",
                                    cfg_name="smol_135m_config"), 1200)
                if mfu is not None:
                    mfu.pop("fwd_mfu", None)     # no meaningful CPU peak
                    mfu.pop("train_mfu", None)
                    extra["smol135m"] = mfu
            except Exception as e:
                log(f"[bench] MFU measurement skipped: {e}")

        try:
            # ---- all_reduce bandwidth sweep (needs the pooled world:
            # the collective spans all workers) ----------------------
            log("[bench] all_reduce bandwidth sweep")
            resp = comm.send_to_all("execute", ALLREDUCE_CELL,
                                    timeout=600)
            m = resp[0]
            if m.data.get("error"):
                log(f"[bench] allreduce cell failed: "
                    f"{m.data.get('traceback', m.data['error'])}")
            else:
                sweep = parse_result_json(m)
                if sweep is not None:
                    extra["allreduce"] = sweep
                    log(f"[bench] allreduce: {sweep}")
        except Exception as e:
            log(f"[bench] allreduce sweep skipped: {e}")

        # Snapshot the observability registry into the BENCH json so
        # perf runs carry comms/retry counters alongside the timings
        # (the coordinator's codec wire hook has been counting every
        # frame of the run).  Best-effort like the other context
        # measurements.
        try:
            from nbdistributed_tpu.observability import metrics as _obsm
            snap = _obsm.registry().to_json()
            extra["observability_metrics"] = {
                "retries_sent": comm.retries_sent,
                "wire_counters": snap.get("counters", {}),
            }
        except Exception as e:
            log(f"[bench] metrics snapshot skipped: {e}")

        try:
            extra["flight_recorder"] = measure_flight_recorder(comm)
            log(f"[bench] flight recorder: {extra['flight_recorder']}")
        except Exception as e:
            log(f"[bench] flight recorder measurement skipped: {e}")

        try:
            tel = measure_telemetry_peaks(comm)
            if tel:
                extra["telemetry"] = tel
                log(f"[bench] telemetry peaks: {tel}")
        except Exception as e:
            log(f"[bench] telemetry summary skipped: {e}")

        # The pooled world's job is done.  Tear it down (blocking)
        # BEFORE the per-family measurements: two processes share the
        # one chip's HBM, so the pooled workers must be gone before a
        # family worker attaches.
        _teardown(comm, pm, world)
        comm = pm = None

        # Elastic pools (ISSUE 16): cold vs warm first-cell compile,
        # resize drain-barrier wall-clock, migration end-to-end — in
        # CPU pools of its own, after the bench world is gone.
        try:
            el = measure_elastic()
            if el:
                extra["elastic"] = el
                log(f"[bench] elastic: {el}")
        except Exception as e:
            log(f"[bench] elastic measurement skipped: {e}")

        # Serving fast path (ISSUE 17): closed-loop loadgen against a
        # paged multi-rank decode plane — sustained tokens/s + p99
        # TTFT/TPOT, then shed rate at 2x overload.
        try:
            sv = measure_serving()
            if sv:
                extra["serving"] = sv
                log(f"[bench] serving: {sv}")
        except Exception as e:
            log(f"[bench] serving measurement skipped: {e}")

        # Training integrity guard (ISSUE 19): guarded vs unguarded
        # DDP step rate + the audit step's fingerprint cost.
        try:
            gd = measure_trainguard()
            if gd:
                extra["trainguard"] = gd
                log(f"[bench] trainguard: {gd}")
        except Exception as e:
            log(f"[bench] trainguard measurement skipped: {e}")

        # Bulk data plane (ISSUE 20): chunked vs legacy push/pull
        # throughput + compression ratio, in a 1-worker world of its
        # own.
        try:
            tx = measure_transfer()
            if tx:
                extra["transfer"] = tx
                log(f"[bench] transfer: {tx}")
        except Exception as e:
            log(f"[bench] transfer measurement skipped: {e}")

        result = {
            "metric": f"ddp_linear1024_steps_per_s_cellwise_{backend}"
                      f"_x{world}",
            "value": round(steps_per_s, 2),
            "unit": "steps/s",
            "vs_baseline": round(vs_baseline, 2),
            "extra": extra,
        }
        if backend == "tpu":
            # Every heavy measurement family runs in its own fresh
            # worker process (see measure_family's docstring for why).
            # The snapshot persists after EVERY family (merge-aware),
            # so a tunnel death or outer-timeout kill mid-run keeps
            # everything measured up to that point; the final persist
            # stamps the completed run.  ``extra`` is shared by
            # reference with ``result``, so each persist sees the
            # families measured so far.
            path = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "BENCH_TPU_LAST.json")

            def _persist(name=None):
                try:
                    persist_tpu_snapshot(
                        path, result, extra,
                        stamp=None if name is None else [name])
                except OSError as e:
                    log(f"[bench] could not persist TPU snapshot: {e}")

            run_families(backend, tpu_families(), extra,
                         on_family=_persist)
            # Final stamp: only keys never stamped (overhead/allreduce
            # rows) get `now`; measured families keep their times.
            # Families an EARLIER window measured but this run did not
            # (budget/flap skips) are annotated onto the printed line
            # from the snapshot persist's OWN return value (never a
            # re-read — a failed write must not mislabel this run's
            # live families as stale carried data).
            try:
                snap = persist_tpu_snapshot(path, result, extra,
                                            stamp=[])
                carried = {k: snap["family_measured_at"].get(k)
                           for k in snap["carried_from_previous"]}
                if carried:
                    extra["carried_families"] = carried
                    extra["snapshot_file"] = os.path.basename(path)
            except OSError as e:
                log(f"[bench] could not persist TPU snapshot: {e}")
        else:
            # CPU fallback: attach the last live on-chip measurement
            # (clearly labeled with its timestamp) so a tunnel outage
            # at bench time doesn't erase the round's TPU evidence.
            try:
                with open(os.path.join(os.path.dirname(
                        os.path.abspath(__file__)),
                        "BENCH_TPU_LAST.json")) as f:
                    result["extra"]["last_live_tpu_run"] = json.load(f)
            except (OSError, ValueError):
                # Missing or corrupt snapshot must never sink an
                # otherwise-successful fallback run.
                pass
        print(json.dumps(result), flush=True)
        return 0
    except Exception:
        import traceback
        log(f"[bench] {backend} run failed:\n{traceback.format_exc()}")
        return 1
    finally:
        if pm is not None or comm is not None:
            _teardown(comm, pm, world)


if __name__ == "__main__":
    sys.exit(main())
