"""Benchmark: DDP train-step throughput driven cell-by-cell through the
full framework stack (BASELINE.json config #3: "4-rank DDP
nn.Linear(1024,1024) SGD loop driven cell-by-cell via %%distributed").

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

What it measures: the coordinator spawns workers (one per available
accelerator — on a 1-chip host, one TPU worker), sends each training
step as its own ``execute`` cell over the control plane, and measures
end-to-end steps/second — i.e. compute + the interactive framework's
full per-cell overhead.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so
the comparison point is the reference's *architectural* per-cell floor:
its coordinator polls the display buffer and the ZMQ socket at 100 ms
each, bounding any cell-by-cell loop at ~0.2 s/cell + compute
(SURVEY §3.2 "latency floor ~200 ms per cell").  vs_baseline =
our_steps_per_s / (1 / (0.2 + measured_compute_s)).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from nbdistributed_tpu.manager import ProcessManager, topology
from nbdistributed_tpu.messaging import CommunicationManager

STEPS = 60
WARMUP = 5

SETUP = """
import jax, jax.numpy as jnp, optax
key = jax.random.PRNGKey(rank)
W = jax.random.normal(key, (1024, 1024), jnp.float32) * 0.02
b = jnp.zeros((1024,), jnp.float32)
opt = optax.sgd(1e-3)
state = opt.init((W, b))
x = jax.random.normal(jax.random.PRNGKey(100 + rank), (256, 1024))
y = jax.random.normal(jax.random.PRNGKey(200 + rank), (256, 1024))

def loss_fn(params, x, y):
    W, b = params
    pred = x @ W + b
    return jnp.mean((pred - y) ** 2)

if world_size > 1:
    # DDP: jit the two halves and all-reduce grads eagerly in between
    # (eager collectives cannot be traced into jit).
    @jax.jit
    def local_grads(params, x, y):
        return jax.value_and_grad(loss_fn)(params, x, y)

    @jax.jit
    def apply_grads(params, state, g):
        u, state = opt.update(g, state, params)
        return optax.apply_updates(params, u), state

    def local_step(params, state, x, y):
        l, g = local_grads(params, x, y)
        g = jax.tree.map(lambda t: all_reduce(t, "mean"), g)
        params, state = apply_grads(params, state, g)
        return params, state, l
else:
    # Single worker: one fused XLA program, no collective needed.
    @jax.jit
    def local_step(params, state, x, y):
        l, g = jax.value_and_grad(loss_fn)(params, x, y)
        u, state = opt.update(g, state, params)
        return optax.apply_updates(params, u), state, l

params = (W, b)
params, state, _ = local_step(params, state, x, y)  # compile
jax.block_until_ready(params)
'ready'
"""

STEP_CELL = """
params, state, loss_val = local_step(params, state, x, y)
jax.block_until_ready(params)
float(loss_val)
"""


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    backend = topology.detect_backend()
    # World size: NBD_BENCH_WORLD env overrides; default is one worker
    # per TPU chip on this host (the bench host has 1), or 2 CPU/gloo
    # workers so the DDP all_reduce branch is a real cross-process
    # collective.
    default_world = "1" if backend == "tpu" else "2"
    world = int(os.environ.get("NBD_BENCH_WORLD", default_world))
    rc = run(backend, world)
    if rc != 0 and backend == "tpu":
        # A flaky TPU tunnel must not leave the driver without a number:
        # rerun on a 2-process CPU/gloo world (the metric name carries
        # the backend, so the JSON line stays honest about what ran).
        log("[bench] TPU run failed (traceback above); "
            "falling back to cpu world")
        rc = run("cpu", max(2, world))
    return rc


def run(backend: str, world: int) -> int:
    log(f"[bench] backend={backend} world={world}")

    comm = None
    pm = ProcessManager()
    try:
        comm = CommunicationManager(num_workers=world, timeout=300)
        pm.add_death_callback(lambda r, rc: comm.mark_worker_dead(r))
        pm.start_workers(world, comm.port, backend=backend)
        from nbdistributed_tpu.manager import wait_until_ready
        wait_until_ready(comm, pm, 240)
        log("[bench] workers attached; running setup cell")
        resp = comm.send_to_all("execute", SETUP, timeout=600)
        for r, m in resp.items():
            if m.data.get("error"):
                log(f"[bench] setup failed on rank {r}: "
                    f"{m.data['traceback']}")
                return 1

        for _ in range(WARMUP):
            comm.send_to_all("execute", STEP_CELL, timeout=600)

        # compute = worker-side measured duration (excludes the control
        # plane), collected from the same steps we time end-to-end
        durations = []
        t0 = time.time()
        for i in range(STEPS):
            resp = comm.send_to_all("execute", STEP_CELL, timeout=600)
            for r, m in resp.items():
                if m.data.get("error"):
                    log(f"[bench] step {i} failed on rank {r}")
                    return 1
            durations.append(max(m.data["duration_s"]
                                 for m in resp.values()))
        elapsed = time.time() - t0
        steps_per_s = STEPS / elapsed
        durations.sort()
        compute = durations[len(durations) // 2]
        overhead_ms = (elapsed / STEPS - compute) * 1000

        # Reference architectural floor: 100ms display poll + 100ms ZMQ
        # poll per cell (SURVEY §3.2) on top of the same compute.
        ref_floor_steps_per_s = 1.0 / (0.2 + compute)
        vs_baseline = steps_per_s / ref_floor_steps_per_s

        log(f"[bench] {STEPS} cell-steps in {elapsed:.2f}s; "
            f"compute={compute*1000:.2f}ms/step, "
            f"framework overhead={overhead_ms:.2f}ms/step")
        print(json.dumps({
            "metric": f"ddp_linear1024_steps_per_s_cellwise_{backend}"
                      f"_x{world}",
            "value": round(steps_per_s, 2),
            "unit": "steps/s",
            "vs_baseline": round(vs_baseline, 2),
        }), flush=True)
        return 0
    except Exception:
        import traceback
        log(f"[bench] {backend} run failed:\n{traceback.format_exc()}")
        return 1
    finally:
        try:
            comm.post(list(range(world)), "shutdown")
            time.sleep(0.3)
        except Exception:
            pass
        pm.shutdown()
        if comm is not None:
            comm.shutdown()


if __name__ == "__main__":
    sys.exit(main())
