#!/bin/bash
# Axon-tunnel watcher: probe every ~10 min; on the first live probe,
# immediately run the full on-chip bench and the flash/decode block
# sweep, then keep watching (the tunnel flaps for hours at a time —
# see BENCH_ATTEMPTS_r03.md).  Logs to $LOGDIR.
#
# Probe protocol: device discovery HANGS while the tunnel is down (it
# does not error), so a 60 s timeout kill means "down".
LOGDIR=${LOGDIR:-/tmp/tpu_watch}
mkdir -p "$LOGDIR"
cd "$(dirname "$0")"
while true; do
    ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
    if timeout 60 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$ts LIVE — running bench.py + tune_flash.py" >> "$LOGDIR/probes.log"
        timeout 4500 python -u bench.py \
            > "$LOGDIR/bench_$ts.out" 2> "$LOGDIR/bench_$ts.log"
        pkill -9 -f "nbdistributed_tpu.runtime.worker" 2>/dev/null
        timeout 3600 python -u tune_flash.py \
            > "$LOGDIR/tune_$ts.out" 2> "$LOGDIR/tune_$ts.log"
        # The tune wrote ops/tuned_blocks.json; fresh workers import
        # it, so re-measuring just the kernel families captures the
        # post-tuning numbers (merged into BENCH_TPU_LAST.json).
        NBD_BENCH_ONLY=flash_attn,decode timeout 1800 python -u bench.py \
            > "$LOGDIR/retune_$ts.out" 2> "$LOGDIR/retune_$ts.log"
        # Where-does-the-time-go breakdown (VERDICT r3 item 8):
        # writes PROFILE_1B.json at the repo root.
        timeout 1200 python -u profile_attrib.py \
            > "$LOGDIR/profile_$ts.out" 2> "$LOGDIR/profile_$ts.log"
        # Kernel tests on the real chip: Mosaic enforces block-shape
        # rules the CPU interpreter does not (two real bugs found that
        # way this round).  Single-device selection only.
        NBD_TEST_TPU=1 timeout 2400 python -m pytest \
            tests/unit/test_decode.py tests/unit/test_attention.py \
            -q -k "not mesh and not tp_mesh" \
            > "$LOGDIR/tputests_$ts.out" 2>&1
        echo "$ts done (bench+tune+tests complete; re-arming)" >> "$LOGDIR/probes.log"
        sleep 3600   # one capture per window is enough; re-arm hourly
    else
        echo "$ts DOWN" >> "$LOGDIR/probes.log"
        sleep 540
    fi
done
