#!/bin/bash
# Axon-tunnel watcher: probe every ~10 min; on the first live probe,
# immediately run the full on-chip bench and the flash/decode block
# sweep, then keep watching (the tunnel flaps for hours at a time —
# see BENCH_ATTEMPTS_r03.md).  Logs to $LOGDIR.
#
# Probe protocol: device discovery HANGS while the tunnel is down (it
# does not error), so a 60 s timeout kill means "down".
LOGDIR=${LOGDIR:-/tmp/tpu_watch}
mkdir -p "$LOGDIR"
cd "$(dirname "$0")"
# Persistent XLA compilation cache, inherited by every child process
# (bench workers, tune, profile, on-chip tests): cold compiles are
# ~10 min of every window, and the tune -> tuned-re-measure -> full
# bench chain recompiles the same programs in fresh processes.  With
# the cache they compile once per window, and window N+1 skips even
# that.  Write failures degrade silently (raise_persistent_cache_errors
# defaults to False) — worst case is a cold compile, never a crash.
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=2
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
while true; do
    ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
    if timeout 60 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$ts LIVE — kernel rows, tune, tuned full bench" >> "$LOGDIR/probes.log"
        # 0. Timing-health preflight (~3 min): every window's noise
        #    profile (spikes, result-cache hits) goes on the record
        #    before any number is measured — see BENCH_ATTEMPTS_r05.md.
        timeout 600 python -u tools/probe_timing.py \
            > "$LOGDIR/preflight_$ts.out" 2>&1
        # Window plan, ordered by verdict priority so a SHORT window
        # still lands the headline artifacts:
        # 1. Quick kernel families first (~30 min incl. cold compile):
        #    guarantees untuned flash/decode rows even if the tunnel
        #    dies early.
        NBD_BENCH_ONLY=flash_attn,decode timeout 2400 python -u bench.py \
            > "$LOGDIR/kernels_$ts.out" 2> "$LOGDIR/kernels_$ts.log"
        pkill -9 -f "nbdistributed_tpu.runtime.worker" 2>/dev/null
        # 2. Block-size tuning -> ops/tuned_blocks.json (the round-4/5
        #    verdicts' #1 ask is the TUNED flash number).  The sweep
        #    checkpoints the table after EVERY shape, so a mid-sweep
        #    tunnel death still lands the headline gqa entry.
        timeout 3600 python -u tune_flash.py \
            > "$LOGDIR/tune_$ts.out" 2> "$LOGDIR/tune_$ts.log"
        # 2b. Quick TUNED kernel re-measure: fresh workers import the
        #     tuned table — the headline tuned-flash number lands here,
        #     ~15 min in, even if the window dies during the full bench.
        NBD_BENCH_ONLY=flash_attn,decode timeout 2400 python -u bench.py \
            > "$LOGDIR/tuned_kernels_$ts.out" 2> "$LOGDIR/tuned_kernels_$ts.log"
        pkill -9 -f "nbdistributed_tpu.runtime.worker" 2>/dev/null
        # 3. FULL bench: fresh workers import the tuned table, so every
        #    family (MFU policy table, decode roofline, speculative,
        #    serving + prefix admission, 7B-int8, MoE dispatch) is
        #    measured WITH tuned kernels in one pass — no separate
        #    retune step needed.
        # 3 h budget: the family list grew (long-context MFU row) and
        # the snapshot now persists after every family, so a long run
        # can only gain — a mid-run kill keeps everything measured.
        timeout 10800 python -u bench.py \
            > "$LOGDIR/bench_$ts.out" 2> "$LOGDIR/bench_$ts.log"
        pkill -9 -f "nbdistributed_tpu.runtime.worker" 2>/dev/null
        # 4. Where-does-the-time-go breakdown (VERDICT r3 item 8):
        #    writes PROFILE_1B.json at the repo root.
        timeout 1200 python -u profile_attrib.py \
            > "$LOGDIR/profile_$ts.out" 2> "$LOGDIR/profile_$ts.log"
        # 5. Kernel tests on the real chip: Mosaic enforces block-shape
        #    rules the CPU interpreter does not (two real bugs found
        #    that way in round 3).  Single-device selection only.
        NBD_TEST_TPU=1 timeout 2400 python -m pytest \
            tests/unit/test_decode.py tests/unit/test_attention.py \
            -q -k "not mesh and not tp_mesh" \
            > "$LOGDIR/tputests_$ts.out" 2>&1
        echo "$ts done (kernels+tune+bench+profile+tests; re-arming)" >> "$LOGDIR/probes.log"
        sleep 3600   # one capture per window is enough; re-arm hourly
    else
        echo "$ts DOWN" >> "$LOGDIR/probes.log"
        # 4-min cadence: the 2026-08-01 window lasted ~35 min total —
        # a 9-min probe gap can eat a quarter of a window.
        sleep 240
    fi
done
