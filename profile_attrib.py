"""Profile-attribute the flagship forward: where does the non-MFU
time go?

Runs the tinyllama-1.1B forward (bench shape B8 S2048 bf16 flash)
under ``jax.profiler.trace``, parses the Chrome-trace device lanes,
and buckets device time into: flash-attention custom calls, GEMM
fusions (dot/convolution), other fusions (elementwise/layernorm/
rotary), and infeed/outfeed/host.  Writes ``PROFILE_1B.json`` at the
repo root — the VERDICT round-3 item 8 breakdown — and prints it.

Unattended-capture friendly (tpu_watch.sh runs it after the bench):
any failure degrades to an error record, never a crash loop.

``NBD_PROFILE_CPU_SMOKE=1`` shrinks to the tiny config on CPU to
validate the harness end-to-end without a chip.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
SMOKE = bool(os.environ.get("NBD_PROFILE_CPU_SMOKE"))


def _bucket(name: str) -> str:
    n = name.lower()
    if "flash" in n or "custom-call" in n or "custom_call" in n:
        return "flash_attention"
    if "dot" in n or "conv" in n or "gemm" in n or "matmul" in n:
        return "gemm"
    if any(t in n for t in ("infeed", "outfeed", "copy", "transfer",
                            "reshape", "transpose")):
        return "data_movement"
    if "fusion" in n or "loop" in n:
        return "other_fusion"
    return "other"


def _parse_trace(trace_dir: str) -> dict:
    """Aggregate device-lane complete events by bucket from the
    newest trace.json.gz under ``trace_dir``."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime)
    if not paths:
        return {"error": "no trace.json.gz produced"}
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # Device lanes: pid whose process_name metadata mentions the
    # accelerator (TPU/device); fall back to all X events.
    dev_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = str(e.get("args", {}).get("name", "")).lower()
            if any(t in pname for t in ("tpu", "device", "/device",
                                        "xla")):
                dev_pids.add(e.get("pid"))
    buckets: dict[str, float] = {}
    names: dict[str, float] = {}
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if dev_pids and e.get("pid") not in dev_pids:
            continue
        nm_raw = str(e.get("name", ""))
        # Host python-trace frames (only reached in the no-device-lane
        # fallback, e.g. CPU smoke) would swamp the op accounting.
        if nm_raw.startswith("$") or ".py:" in nm_raw \
                or "ThunkExecutor" in nm_raw:
            continue
        dur = float(e["dur"])          # microseconds
        total += dur
        b = _bucket(e.get("name", ""))
        buckets[b] = buckets.get(b, 0.0) + dur
        nm = e.get("name", "?")[:80]
        names[nm] = names.get(nm, 0.0) + dur
    if total == 0.0:
        return {"error": "no timed device events in trace",
                "trace_file": paths[-1]}
    top = sorted(names.items(), key=lambda kv: -kv[1])[:15]
    return {
        "total_device_ms": round(total / 1e3, 2),
        "buckets_ms": {k: round(v / 1e3, 2)
                       for k, v in sorted(buckets.items(),
                                          key=lambda kv: -kv[1])},
        "buckets_pct": {k: round(100 * v / total, 1)
                        for k, v in sorted(buckets.items(),
                                           key=lambda kv: -kv[1])},
        "top_ops": [{"name": n, "ms": round(v / 1e3, 2)}
                    for n, v in top],
        "trace_file": paths[-1],
    }


def main() -> int:
    import jax
    import jax.numpy as jnp

    from nbdistributed_tpu.models import (forward, init_params,
                                          tiny_config,
                                          tinyllama_1b_config)

    if jax.default_backend() != "tpu" and not SMOKE:
        print("profile_attrib.py needs a live TPU "
              f"(backend={jax.default_backend()})", file=sys.stderr)
        return 1

    if SMOKE:
        cfg = tiny_config(dtype=jnp.float32, use_flash=True)
        B, S, steps = 2, 64, 2
    else:
        cfg = tinyllama_1b_config(dtype=jnp.bfloat16, use_flash=True)
        B, S, steps = 8, 2048, 3

    out: dict = {"config": type(cfg).__name__,
                 "shape": f"B{B} S{S}",
                 "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                              time.gmtime())}
    try:
        params = init_params(jax.random.PRNGKey(0), cfg)
        tok = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0,
                                 cfg.vocab_size)
        f = jax.jit(lambda p, t: forward(p, t, cfg))
        float(f(params, tok)[0, 0, 0])                 # compile outside
        trace_dir = "/tmp/nbd_profile"
        os.makedirs(trace_dir, exist_ok=True)
        with jax.profiler.trace(trace_dir):
            o = None
            for i in range(steps):
                # Fresh token values per step and a value fetch at the
                # end: the tunnel serves repeated identical inputs from
                # a result cache and async-acks block_until_ready, so
                # the naive loop would trace ~zero device time.
                o = f(params, (tok + i + 1) % cfg.vocab_size)
            float(o[0, 0, 0])
        out.update(_parse_trace(trace_dir))
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"

    path = os.path.join("/tmp" if SMOKE else REPO, "PROFILE_1B.json")
    with open(path + ".tmp", "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(path + ".tmp", path)
    print(json.dumps(out, indent=1))
    return 0 if "error" not in out else 1


if __name__ == "__main__":
    sys.exit(main())
